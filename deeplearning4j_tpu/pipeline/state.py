"""Persistent pipeline state machine: the crash-safe spine of the
continuous-training loop.

A pipeline *run* moves through fixed stages::

    IDLE -> TRAIN -> EVAL -> CANARY -> PROMOTE | ROLLBACK

Every stage is a two-phase record in an append-only journal — ``enter``
when work begins, ``commit`` when it finished — so a crash at any point
leaves an unambiguous resume point: the stage that was entered but never
committed.  The terminal stages are exclusive per run (journal-enforced):
a run commits exactly one ``PROMOTE`` or one ``ROLLBACK``, never both and
never two, which is what makes a restarted pipeline unable to
double-promote.

Fencing reuses the elastic supervisor's ``GenerationLedger`` commit-stamp
pattern (``parallel/elastic.py``): each pipeline *process* acquires an
ownership token; every journal append re-reads the owner file first and
refuses to write under a stale token (:class:`StalePipelineError`), and
acquisition snapshots the sequence numbers the previous owner had
committed — a zombie's append that slips past the re-read race is dropped
on replay because its seq is not in its token's fenced snapshot.  The
result is the same guarantee the elastic ledger gives checkpoints: a
process that lost ownership can still write bytes, but nothing it writes
after the fence is ever part of the recovered state.

Fault injection: after every journal append the machine calls
``util.faultinject.on_step("pipeline", seq)`` — a fault plan entry like
``{"type": "kill", "worker": "pipeline", "step": 7}`` SIGKILLs the
pipeline process at the 7th journal record, which is how CI proves that
a restart mid-CANARY resumes and converges to the same terminal state.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.observe import trace as _trace
from deeplearning4j_tpu.util import faultinject
from deeplearning4j_tpu.util.fsio import atomic_write_text

OWNER_FILE = "pipeline_owner.json"
JOURNAL_FILE = "pipeline_journal.jsonl"

STAGES = ("TRAIN", "EVAL", "CANARY", "PROMOTE", "ROLLBACK")
TERMINAL_STAGES = ("PROMOTE", "ROLLBACK")

# stage -> stages that may legally be ENTERED after it commits
_NEXT: Dict[str, tuple] = {
    "TRAIN": ("EVAL", "ROLLBACK"),   # ROLLBACK: watchdog-rejected candidate
    "EVAL": ("CANARY", "PROMOTE", "ROLLBACK"),
    "CANARY": ("PROMOTE", "ROLLBACK"),
    "PROMOTE": (),
    "ROLLBACK": (),
}

# the fault-injection slot id for every pipeline-process transition
FAULT_SLOT = "pipeline"


class StalePipelineError(RuntimeError):
    """This process lost pipeline ownership (another process acquired the
    journal); its transitions are un-committable."""


class IllegalTransition(RuntimeError):
    """The requested stage is not legal from the current state."""


class AlreadyDecided(RuntimeError):
    """The run already committed a terminal stage — a second
    promote/rollback is refused (single-decision semantics)."""


class PipelineJournal:
    """Fenced append-only journal under ``directory``.

    ``acquire()`` takes ownership: it fences every earlier owner by
    snapshotting the seqs each had appended (the elastic ledger's
    ``known_steps``), then installs a fresh token.  ``append()`` re-reads
    the owner file and refuses stale tokens.  ``records()`` replays only
    *eligible* lines: the current owner's, plus fenced owners' lines that
    are inside their snapshot — a zombie's post-fence line parses fine but
    is not part of recovered state.
    """

    def __init__(self, directory: str):
        self.directory = str(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.owner_path = os.path.join(self.directory, OWNER_FILE)
        self.journal_path = os.path.join(self.directory, JOURNAL_FILE)
        self._next_seq: Optional[int] = None  # cached at acquire()

    # ------------------------------------------------------------ ownership
    def _read_owner(self) -> Optional[dict]:
        try:
            with open(self.owner_path, encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError):
            return None

    def _repair_torn_tail(self) -> None:
        """Terminate a torn final line (a crash mid-write) so the NEXT
        append starts on a fresh line instead of concatenating into the
        torn JSON and vanishing from replay. The torn record itself stays
        unparseable — it never committed — but everything after it must."""
        try:
            with open(self.journal_path, "rb") as fh:
                fh.seek(-1, os.SEEK_END)
                last = fh.read(1)
        except (OSError, ValueError):  # missing or empty journal
            return
        if last != b"\n":
            with open(self.journal_path, "ab") as fh:
                fh.write(b"\n")
                fh.flush()
                os.fsync(fh.fileno())

    def acquire(self, meta: Optional[dict] = None) -> str:
        """Become the journal's owner; returns the new token. Any earlier
        owner is fenced with a snapshot of the seqs it has committed so
        far — everything it appends afterwards is ineligible."""
        self._repair_torn_tail()
        owner = self._read_owner() or {"lineage": []}
        seqs_by_token: Dict[str, List[int]] = {}
        for rec in self._raw_records():
            seqs_by_token.setdefault(rec["token"], []).append(rec["seq"])
        for entry in owner.get("lineage", []):
            if not entry.get("fenced"):
                entry["fenced"] = True
                entry["known_seqs"] = sorted(
                    seqs_by_token.get(entry["token"], []))
        token = f"{os.getpid():x}-{os.urandom(8).hex()}"
        owner.setdefault("lineage", []).append(
            {"token": token, "fenced": False, "known_seqs": []})
        owner["token"] = token
        owner["acquired_ms"] = int(time.time() * 1000)
        if meta:
            owner["meta"] = meta
        atomic_write_text(self.owner_path, json.dumps(owner, indent=1),
                          fsync=True)
        # cache the next seq so appends don't re-scan the whole journal
        # (O(n^2) over a long-lived pipeline otherwise). A fenced zombie
        # appending concurrently may collide on a seq — harmless: replay
        # eligibility is keyed on (token, seq) and the zombie's seq is
        # outside its fence snapshot either way.
        self._next_seq = self._line_count() + 1
        return token

    def current_token(self) -> Optional[str]:
        owner = self._read_owner()
        return None if owner is None else owner.get("token")

    # -------------------------------------------------------------- append
    def append(self, token: str, record: Dict[str, Any]) -> int:
        """Append one record under ``token``; returns its seq. Re-reads
        the owner file first: a stale token raises
        :class:`StalePipelineError` and writes nothing."""
        if self.current_token() != token:
            raise StalePipelineError(
                f"pipeline ownership lost (token {token[:8]}… fenced); "
                "this process must not commit transitions")
        if self._next_seq is None:  # append without acquire (tests)
            self._next_seq = self._line_count() + 1
        seq = self._next_seq
        rec = dict(record)
        rec["seq"] = seq
        rec["token"] = token
        rec["ts"] = time.time()
        # trace correlation (the LogRecord contract): a journal line
        # written inside a traced pipeline run carries the active span's
        # ids, so a promote/rollback decision is joinable with the spans
        # and logs that caused it. Reads the context directly (ids are
        # tracer-independent) so an explicitly-passed runner tracer
        # correlates too; no open span → no fields, no cost.
        trace_id, span_id = _trace.current_span_ids()
        if trace_id is not None:
            rec.setdefault("trace_id", trace_id)
            rec.setdefault("span_id", span_id)
        line = json.dumps(rec, sort_keys=True)
        with open(self.journal_path, "a", encoding="utf-8") as fh:
            fh.write(line + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        self._next_seq = seq + 1
        return seq

    def _line_count(self) -> int:
        n = 0
        try:
            with open(self.journal_path, encoding="utf-8") as fh:
                for line in fh:
                    if line.endswith("\n"):
                        n += 1
        except OSError:
            pass
        return n

    # --------------------------------------------------------------- replay
    def _raw_records(self) -> List[dict]:
        out: List[dict] = []
        try:
            with open(self.journal_path, encoding="utf-8") as fh:
                lines = fh.readlines()
        except OSError:
            return out
        for line in lines:
            if not line.endswith("\n"):
                continue  # torn final line: that record never committed
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "seq" in rec and "token" in rec:
                out.append(rec)
        return out

    def records(self, eligible_only: bool = True) -> List[dict]:
        recs = self._raw_records()
        if not eligible_only:
            return recs
        owner = self._read_owner()
        if owner is None:
            return []
        eligible: Dict[str, Optional[set]] = {}
        for entry in owner.get("lineage", []):
            eligible[entry["token"]] = (
                set(entry.get("known_seqs", [])) if entry.get("fenced")
                else None)  # None = unfenced: everything counts
        out = []
        for rec in recs:
            known = eligible.get(rec["token"], set())
            if known is None or rec["seq"] in known:
                out.append(rec)
        return out


class PipelineState:
    """A snapshot of where the machine is: ``run`` (0 = none yet),
    ``stage`` (``"IDLE"`` or a :data:`STAGES` member), whether that stage
    has committed, and the stage's recorded data."""

    __slots__ = ("run", "stage", "committed", "data")

    def __init__(self, run: int, stage: str, committed: bool, data: dict):
        self.run = run
        self.stage = stage
        self.committed = committed
        self.data = data

    def to_dict(self) -> dict:
        return {"run": self.run, "stage": self.stage,
                "committed": self.committed, "data": dict(self.data)}

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"PipelineState(run={self.run}, stage={self.stage}, "
                f"committed={self.committed})")


class PipelineStateMachine:
    """The journaled stage machine one continuous-training pipeline runs on.

    Constructing it ACQUIRES ownership of ``directory`` (fencing any
    previous process) and replays the eligible journal into the in-memory
    state, so ``resume_point()`` immediately says where a crashed
    predecessor stopped.  All mutations go through :meth:`begin_run`,
    :meth:`enter`, :meth:`commit` and :meth:`note`; each appends a fenced
    journal record and fires the ``"pipeline"`` fault-injection step hook.

    ``metrics`` (optional ``observe.metrics.MetricsRegistry``) exports
    ``pipeline_stage{pipeline}`` (index into IDLE+STAGES),
    ``pipeline_transitions_total{pipeline,stage,event}`` and
    ``pipeline_runs_total{pipeline,outcome}``.
    """

    def __init__(self, directory: str, *, name: str = "default",
                 metrics=None):
        self.name = name
        self.journal = PipelineJournal(directory)
        self.token = self.journal.acquire(meta={"name": name})
        self._m_stage = self._m_trans = self._m_runs = None
        if metrics is not None:
            self._m_stage = metrics.gauge(
                "pipeline_stage",
                "Current pipeline stage (0=IDLE, then TRAIN..ROLLBACK)",
                ("pipeline",))
            self._m_trans = metrics.counter(
                "pipeline_transitions_total",
                "Journaled pipeline stage transitions",
                ("pipeline", "stage", "event"))
            self._m_runs = metrics.counter(
                "pipeline_runs_total",
                "Completed pipeline runs by terminal outcome",
                ("pipeline", "outcome"))
        self._replay()
        self._export_stage()

    # -------------------------------------------------------------- replay
    def _replay(self) -> None:
        self.run = 0
        self.stage: Optional[str] = None    # None = IDLE
        self.stage_committed = False
        self.stage_data: dict = {}
        self.terminal: Dict[int, str] = {}  # run -> committed terminal stage
        for rec in self.journal.records():
            event = rec.get("event")
            if event == "run":
                self.run = int(rec["run"])
                self.stage, self.stage_committed = None, False
                self.stage_data = {}
            elif event == "enter":
                self.stage = rec["stage"]
                self.stage_committed = False
                self.stage_data = rec.get("data", {})
            elif event == "commit":
                self.stage = rec["stage"]
                self.stage_committed = True
                self.stage_data = rec.get("data", {})
                if rec["stage"] in TERMINAL_STAGES:
                    self.terminal[int(rec["run"])] = rec["stage"]
            # "note" records are observability only — no state effect

    # ------------------------------------------------------------- queries
    def state(self) -> PipelineState:
        if self.stage is None or self.run in self.terminal:
            return PipelineState(self.run, "IDLE", True, {})
        return PipelineState(self.run, self.stage, self.stage_committed,
                             dict(self.stage_data))

    def resume_point(self) -> Optional[PipelineState]:
        """Where a crashed predecessor stopped: the open run's last stage
        (entered-or-committed), or ``None`` when the journal is at IDLE
        (no run, or the last run reached its terminal commit)."""
        st = self.state()
        return None if st.stage == "IDLE" else st

    def open_empty_run(self) -> bool:
        """True when a run was opened (``begin_run`` journaled) but
        crashed before entering any stage — the runner CONTINUES that run
        instead of opening a new one, preserving the exactly-one-terminal
        -per-run invariant."""
        return (self.run > 0 and self.run not in self.terminal
                and self.stage is None)

    def decided(self, run: Optional[int] = None) -> Optional[str]:
        """The terminal stage a run committed (``None`` while undecided)."""
        return self.terminal.get(self.run if run is None else int(run))

    def stage_history(self, run: Optional[int] = None) -> List[dict]:
        """All eligible records of one run, oldest first."""
        run = self.run if run is None else int(run)
        return [r for r in self.journal.records()
                if int(r.get("run", -1)) == run]

    # ----------------------------------------------------------- mutations
    def _append(self, record: dict) -> int:
        seq = self.journal.append(self.token, record)
        if self._m_trans is not None and record.get("event") in (
                "enter", "commit"):
            self._m_trans.inc(pipeline=self.name,
                              stage=record.get("stage", "?"),
                              event=record["event"])
        self._export_stage()
        # the CI crash lever: a planned kill/stall fires at this exact seq
        faultinject.on_step(FAULT_SLOT, seq)
        return seq

    def _export_stage(self) -> None:
        if self._m_stage is None:
            return
        st = self.state()
        idx = 0 if st.stage == "IDLE" else 1 + STAGES.index(st.stage)
        self._m_stage.set(idx, pipeline=self.name)

    def begin_run(self, **data) -> int:
        """Open the next run; only legal from IDLE."""
        if self.state().stage != "IDLE":
            raise IllegalTransition(
                f"run {self.run} is still open at stage {self.stage}; "
                "finish it (terminal commit) before beginning a new run")
        self.run += 1
        self.stage, self.stage_committed, self.stage_data = None, False, {}
        self._append({"event": "run", "run": self.run, "data": data})
        return self.run

    def enter(self, stage: str, **data) -> int:
        """Journal the start of ``stage`` work. Legality: TRAIN first,
        then along :data:`_NEXT` edges; re-entering the same uncommitted
        stage is allowed (a resumed process restarts the stage's work)."""
        if stage not in STAGES:
            raise IllegalTransition(f"unknown stage {stage!r}")
        if self.run == 0 or self.run in self.terminal:
            raise IllegalTransition(
                f"no open run to enter {stage} in (begin_run() first)")
        if self.stage is None:
            legal = ("TRAIN",)
        elif self.stage_committed:
            legal = _NEXT[self.stage]
        elif self.stage == "PROMOTE":
            # an ENTERED promote that cannot complete (candidate weights
            # lost before the commit) may still be re-decided: the run has
            # not decided until a terminal COMMIT lands
            legal = ("PROMOTE", "ROLLBACK")
        else:
            legal = (self.stage,)  # resume: re-enter the crashed stage
        if stage not in legal:
            raise IllegalTransition(
                f"cannot enter {stage} from "
                f"{self.stage or 'run start'}"
                f"{'' if self.stage_committed or not self.stage else ' (uncommitted)'}; "
                f"legal: {legal}")
        if stage in TERMINAL_STAGES and self.run in self.terminal:
            raise AlreadyDecided(
                f"run {self.run} already committed {self.terminal[self.run]}")
        self.stage, self.stage_committed = stage, False
        self.stage_data = dict(data)
        return self._append({"event": "enter", "run": self.run,
                             "stage": stage, "data": data})

    def commit(self, stage: str, **data) -> int:
        """Journal the completion of ``stage``. Terminal stages enforce
        the single-decision rule: a run that already committed PROMOTE or
        ROLLBACK refuses a second terminal commit."""
        if self.stage != stage or self.stage_committed:
            raise IllegalTransition(
                f"commit({stage}) without a matching open enter "
                f"(current: {self.stage}, committed="
                f"{self.stage_committed})")
        if stage in TERMINAL_STAGES:
            if self.run in self.terminal:
                raise AlreadyDecided(
                    f"run {self.run} already committed "
                    f"{self.terminal[self.run]}")
            self.terminal[self.run] = stage
            if self._m_runs is not None:
                self._m_runs.inc(pipeline=self.name, outcome=stage.lower())
        self.stage_committed = True
        self.stage_data = dict(data)
        return self._append({"event": "commit", "run": self.run,
                             "stage": stage, "data": data})

    def note(self, message: str, **data) -> int:
        """Observability-only record (canary ramp steps, operator stops);
        replay ignores it."""
        return self._append({"event": "note", "run": self.run,
                             "stage": self.stage, "message": message,
                             "data": data})
