"""SameDiff standalone graph builder.

Mirrors the reference's SameDiff usage (ND4J's declarative graph API that
backs DL4J's SameDiff layers): declare placeholders and variables, compose
ops with SDVariable algebra, execute, differentiate, and train — all lowered
to single jitted JAX functions.

Run: python examples/08_samediff_graph_builder.py   (CPU-friendly)
"""

import numpy as np

from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig
from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.updaters import Adam


def main():
    rng = np.random.default_rng(0)

    # -- 1. declare a two-layer MLP symbolically ---------------------------
    sd = SameDiff.create()
    x = sd.place_holder("input", shape=(None, 4))
    y = sd.place_holder("label", shape=(None, 3))
    w1 = sd.var("w1", shape=(4, 16))
    b1 = sd.var("b1", value=np.zeros(16))
    w2 = sd.var("w2", shape=(16, 3))
    b2 = sd.var("b2", value=np.zeros(3))

    hidden = sd.nn.tanh(x @ w1 + b1, name="hidden")
    logits = (hidden @ w2 + b2)
    logits.rename("logits")
    probs = sd.nn.softmax(logits, name="probs")
    sd.loss.softmax_cross_entropy(y, logits, name="loss")
    sd.set_loss_variables("loss")

    # -- 2. execute + inspect ----------------------------------------------
    xv = rng.normal(size=(8, 4)).astype(np.float32)
    out = sd.output({"input": xv}, "probs", "hidden")
    print("probs shape:", out["probs"].shape, "hidden shape:", out["hidden"].shape)
    print("inferred logits shape:", sd.get_variable("logits").shape)

    # -- 3. gradients -------------------------------------------------------
    yv = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 8)]
    grads = sd.calculate_gradients({"input": xv, "label": yv}, "w1", "w2")
    print("dL/dw1 norm:", float(np.linalg.norm(grads["w1"])))

    # -- 4. train on a separable toy problem -------------------------------
    n = 512
    cls = rng.integers(0, 3, n)
    feats = rng.normal(size=(n, 4)).astype(np.float32)
    feats[np.arange(n), cls] += 2.5
    labels = np.eye(3, dtype=np.float32)[cls]
    sd.set_training_config(TrainingConfig(
        updater=Adam(0.05),
        data_set_feature_mapping=["input"],
        data_set_label_mapping=["label"]))
    final_loss = sd.fit(DataSet(feats, labels), epochs=60)
    preds = sd.output({"input": feats}, "probs")["probs"].argmax(-1)
    print(f"final loss {final_loss:.4f}  train accuracy {(preds == cls).mean():.3f}")

    # -- 5. save / load -----------------------------------------------------
    import tempfile, os
    path = os.path.join(tempfile.mkdtemp(), "mlp.npz")
    sd.save(path)
    sd2 = SameDiff.load(path)
    preds2 = sd2.output({"input": feats}, "probs")["probs"].argmax(-1)
    assert (preds == preds2).all()
    print("save/load round trip OK ->", path)


if __name__ == "__main__":
    main()
