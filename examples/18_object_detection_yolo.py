"""Object detection end-to-end: TinyYOLO → train → extract detections.

The full reference workflow (``Yolo2OutputLayer.getPredictedObjects`` +
``YoloUtils.nms``): build the zoo TinyYOLO at a reduced input size, train
on synthetic scenes with planted bright squares, then decode the raw
network output into DetectedObject boxes with confidence thresholding and
non-max suppression.

Run: python examples/18_object_detection_yolo.py   (CPU-friendly, ~1 min)
"""

import numpy as np

from deeplearning4j_tpu.nn.layers import DetectedObject
from deeplearning4j_tpu.zoo.models import TINY_YOLO_ANCHORS, TinyYOLO

GRID = 32  # TinyYOLO downsamples 32x: a 128x128 input gives a 4x4 grid


def make_scene(rng, n_classes=2, size=128):
    """One image with one bright square; label [H/32, W/32, 5+C]."""
    g = size // GRID
    x = rng.normal(0.0, 0.1, size=(size, size, 3)).astype(np.float32)
    cls = int(rng.integers(0, n_classes))
    # object center, in pixels; square side encodes the class
    cy, cx = rng.uniform(16, size - 16, 2)
    side = 24 if cls == 0 else 48
    y0, y1 = int(max(cy - side / 2, 0)), int(min(cy + side / 2, size))
    x0, x1 = int(max(cx - side / 2, 0)), int(min(cx + side / 2, size))
    x[y0:y1, x0:x1, cls] += 2.0
    label = np.zeros((g, g, 5 + n_classes), np.float32)
    gy, gx = int(cy // GRID), int(cx // GRID)
    label[gy, gx, 0] = cx / GRID          # center, grid units (absolute)
    label[gy, gx, 1] = cy / GRID
    label[gy, gx, 2] = side / GRID        # size, grid units
    label[gy, gx, 3] = side / GRID
    label[gy, gx, 4] = 1.0                # objectness
    label[gy, gx, 5 + cls] = 1.0
    return x, label


def main():
    rng = np.random.default_rng(7)
    n_classes = 2
    model = TinyYOLO(num_labels=n_classes, input_shape=(3, 128, 128))
    net = model.init()
    print("TinyYOLO built:", len(net.conf.vertices), "vertices")

    data = [make_scene(rng, n_classes) for _ in range(32)]
    xs = np.stack([d[0] for d in data])
    ys = np.stack([d[1] for d in data])
    for epoch in range(3):
        for i in range(0, len(xs), 8):
            net.fit([xs[i:i + 8]], [ys[i:i + 8]])
        print(f"epoch {epoch}: loss {net.score_:.3f}", flush=True)

    # ---- detection extraction (the part the reference user came for) ----
    raw = np.asarray(net.output(xs[:4]))
    yolo_layer = net.conf.vertices["outputs"].obj
    detections = yolo_layer.get_predicted_objects(
        raw, conf_threshold=0.1, nms_threshold=0.4)
    print(f"{len(detections)} detections at conf>=0.1 after NMS")
    for d in detections[:8]:
        assert isinstance(d, DetectedObject)
        (x0, y0), (x1, y1) = d.top_left_xy(), d.bottom_right_xy()
        print(f"  example {d.example}: class {d.predicted_class} "
              f"conf {d.confidence:.2f} box grid-units "
              f"[{x0:.2f},{y0:.2f}]..[{x1:.2f},{y1:.2f}] "
              f"pixels [{x0 * GRID:.0f},{y0 * GRID:.0f}].."
              f"[{x1 * GRID:.0f},{y1 * GRID:.0f}]")


if __name__ == "__main__":
    main()
