"""Production model serving: versioned registry, HTTP front-end, /metrics.

The round-6 serving subsystem (`serving/`) end to end — the layer that turns
a trained or imported model into a network service:

- train a tiny classifier, save it with ModelSerializer, and register the
  ZIP as version 1 of a named model (the registry loads own zips, DL4J
  checkpoints and Keras h5 through the same ModelGuesser path);
- start the `ModelServer` on an ephemeral port and query it with the typed
  client over BOTH wire formats: JSON and the `streaming/codec.py` binary
  array frame;
- retrain and hot-swap version 2 atomically under the live server
  (`ParallelInference.update_model` underneath — in-flight batches finish
  on the old weights), then roll back;
- attach a per-request deadline (the 504 path past expiry — expired work
  never reaches the device) and watch `/readyz`;
- scrape `/metrics` (Prometheus text format) and reconcile the request
  counters and batch-size histogram with what the clients observed.

Run: python examples/24_production_serving.py   (CPU-friendly, a few seconds)
"""

import os
import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.serving import (MetricsRegistry, ModelRegistry,
                                        ModelServer, ModelServingClient)
from deeplearning4j_tpu.util.model_serializer import write_model


def build_and_train(x, y, seed, epochs=6):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=16, n_out=32, activation="relu"))
            .layer(OutputLayer(n_in=32, n_out=3, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ListDataSetIterator(DataSet(x, y), 64, shuffle=True),
            epochs=epochs)
    return net


def main():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(384, 16)).astype(np.float32)
    w = rng.normal(size=(16, 3)).astype(np.float32)
    cls = np.argmax(x @ w, axis=1)
    y = np.eye(3, dtype=np.float32)[cls]

    # -- v1: train, checkpoint, register from the ZIP -----------------------
    net_v1 = build_and_train(x, y, seed=1, epochs=4)
    ckpt = os.path.join(tempfile.mkdtemp(), "classifier.zip")
    write_model(net_v1, ckpt)

    metrics = MetricsRegistry()
    registry = ModelRegistry(metrics=metrics)
    v1 = registry.register("classifier", path=ckpt)
    print(f"registered v{v1} from {ckpt}")

    # -- serve over HTTP ----------------------------------------------------
    server = ModelServer(registry, metrics=metrics, max_inflight=32)
    port = server.start()
    client = ModelServingClient(server.url)
    print(f"serving on port {port}; ready={client.ready()}")

    probe = x[:32]
    out_json = client.predict("classifier", probe)
    out_bin = client.predict("classifier", probe, binary=True)
    acc1 = (out_json.argmax(-1) == cls[:32]).mean()
    print(f"v1 accuracy on probe: {acc1:.3f}; "
          f"json == binary codec: {np.allclose(out_json, out_bin, atol=1e-6)}")

    # -- v2: longer training, atomic hot-swap, rollback ---------------------
    net_v2 = build_and_train(x, y, seed=2, epochs=12)
    v2 = registry.register("classifier", net_v2)   # activates atomically
    acc2 = (client.predict("classifier", probe).argmax(-1) == cls[:32]).mean()
    print(f"hot-swapped to v{v2}: accuracy {acc2:.3f}")
    pinned = client.predict("classifier", probe, version=1)
    print(f"v1 still queryable pinned: "
          f"{np.allclose(pinned, out_json, atol=1e-5)}")
    registry.rollback("classifier")
    print(f"rolled back; live version = "
          f"{registry.get('classifier').current_version}")

    # -- deadlines ----------------------------------------------------------
    ok = client.predict("classifier", probe, deadline_ms=2000)
    print(f"predict under a 2 s deadline: shape {ok.shape}")

    # -- observability: scrape and reconcile --------------------------------
    scraped = client.metrics()
    reqs = scraped["serving_requests_total"]
    total = sum(reqs.values())
    by_status = {}
    for labels, v in reqs.items():
        by_status[dict(labels)["status"]] = \
            by_status.get(dict(labels)["status"], 0) + int(v)
    batches = registry.get("classifier").inference.batches_dispatched
    hist_count = scraped["inference_batch_size_count"][
        (("model", "classifier"),)]
    print(f"/metrics: {total:.0f} requests by status {by_status}; "
          f"batch histogram count {hist_count:.0f} == "
          f"dispatched batches {batches}")
    swaps = {dict(k)["kind"]: int(v)
             for k, v in scraped["serving_model_swaps_total"].items()}
    print(f"swap events: {swaps}")

    # -- graceful drain -----------------------------------------------------
    server.stop(drain=True, shutdown_registry=True)
    print(f"drained and stopped; ready={client.ready()}")


if __name__ == "__main__":
    main()
