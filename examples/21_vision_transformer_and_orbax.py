"""Example 21 — VisionTransformer + the orbax checkpoint path.

The two TPU-native additions from round 3's late session: a ViT zoo model
(patch-embed conv -> shared transformer encoder blocks) trained on a toy
image task, checkpointed through the orbax path with step rotation, then
preemption-resumed.

Run: PYTHONPATH=/root/repo:/root/.axon_site python examples/21_vision_transformer_and_orbax.py
"""

import tempfile

import jax

jax.config.update("jax_platforms", "cpu")  # small demo; skip the TPU tunnel

import numpy as np

from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.util.orbax_checkpoint import OrbaxCheckpointManager
from deeplearning4j_tpu.util.preemption import PreemptionHandler
from deeplearning4j_tpu.zoo import VisionTransformer

# --- 1. a small ViT ---------------------------------------------------------
vit = VisionTransformer(num_labels=2, image_size=16, patch_size=4,
                        n_layers=2, d_model=32, n_heads=4, d_ff=64, seed=7)
print(f"ViT: {vit.num_patches} patches per image")
net = ComputationGraph(vit.conf())
net.init()

# toy task: is the top-left patch bright?
rng = np.random.default_rng(0)
x = rng.normal(0, 0.3, size=(64, 16, 16, 3)).astype(np.float32)
cls = rng.integers(0, 2, 64)
x[cls == 1, :4, :4, :] += 2.0
y = np.eye(2, dtype=np.float32)[cls]

# --- 2. train with rotating orbax checkpoints ------------------------------
with tempfile.TemporaryDirectory() as ckpt_dir:
    with OrbaxCheckpointManager(ckpt_dir, max_to_keep=2,
                                save_interval_steps=10) as mgr:
        for step in range(40):
            net.fit(x, y)
            mgr.save(step, net)
        mgr.wait_until_finished()
        print(f"checkpoints kept: steps {mgr.all_steps()}")
        acc = (np.asarray(net.output_single(x)).argmax(1) == cls).mean()
        print(f"train accuracy: {acc:.2f}")

        # --- 3. "preemption": restore the latest step and keep going -------
        resumed = mgr.restore()
        print(f"restored at iteration {resumed.iteration}")
        resumed.fit(x, y)

    # --- 4. the SIGTERM-armed handler uses the same machinery --------------
    handler = PreemptionHandler(net, ckpt_dir + "/preempt", backend="orbax")
    handler.save()  # what the SIGTERM hook runs in the grace window
    model, state = PreemptionHandler.resume(ckpt_dir + "/preempt")
    print(f"preemption round trip at iteration {state['iteration']}: "
          f"outputs equal = "
          f"{np.allclose(np.asarray(model.output_single(x)), np.asarray(net.output_single(x)), rtol=1e-6)}")
