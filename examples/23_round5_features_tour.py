"""Example 23 — round-5 feature tour: normalizer.bin migration, designed
tensor parallelism, Chinese lattice segmentation, typed unknown words.

Four additions in one runnable script:

1. ``normalizer.bin`` both ways — ship a model WITH its fitted normalizer
   in one DL4J-format zip (``ModelSerializer.java:165-168``), restore both
   on the consumer side (``restoreNormalizerFromFile:707``), reproduce the
   producer's outputs from raw data alone.
2. Designed (Megatron) tensor parallelism — paired column→row Dense specs
   and head-sharded attention over a dp×tp mesh; TP outputs equal the
   replicated model.
3. Chinese lattice segmentation — the bigram-cost Viterbi decoder beats
   greedy longest-match on the classic ambiguity traps.
4. kuromoji-style unknown-word handling — out-of-lexicon spans come back
   as single TYPED tokens (grouped katakana/alpha/numeric runs), not
   per-character soup.

Run: PYTHONPATH=/root/repo:/root/.axon_site \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python examples/23_round5_features_tour.py
"""

import os
import tempfile

import jax

jax.config.update("jax_platforms", "cpu")  # small demo; skip the TPU tunnel

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.normalizers import NormalizerStandardize
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam

# --- 1. normalizer.bin rides the checkpoint zip ----------------------------
print("== 1. normalizer.bin migration (both directions)")

rng = np.random.default_rng(5)
y_idx = rng.integers(0, 3, 512)
x_raw = (rng.normal(size=(512, 8)).astype(np.float32) * 40 + 250)
for i, c in enumerate(y_idx):
    x_raw[i, c] += 90.0
y = np.eye(3, dtype=np.float32)[y_idx]

norm = NormalizerStandardize().fit(DataSet(x_raw, y))
x_norm = np.asarray(norm.transform(DataSet(x_raw, y)).features)

conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2)).list()
        .layer(DenseLayer(n_in=8, n_out=32, activation="relu"))
        .layer(OutputLayer(n_in=32, n_out=3)).build())
producer = MultiLayerNetwork(conf).init()
for _ in range(15):
    producer.fit(x_norm, y)

from deeplearning4j_tpu.modelimport.dl4j import (
    restore_multi_layer_network,
    restore_normalizer,
)
from deeplearning4j_tpu.modelimport.dl4j_export import (
    export_multi_layer_network,
)

with tempfile.TemporaryDirectory() as td:
    zip_path = os.path.join(td, "shipped.zip")
    export_multi_layer_network(producer, zip_path, normalizer=norm)
    consumer_net = restore_multi_layer_network(zip_path)
    consumer_norm = restore_normalizer(zip_path)

x_new = rng.normal(size=(16, 8)).astype(np.float32) * 40 + 250
a = np.asarray(producer.output(
    np.asarray(norm.transform(DataSet(x_new, None)).features)))
b = np.asarray(consumer_net.output(
    np.asarray(consumer_norm.transform(DataSet(x_new, None)).features)))
np.testing.assert_allclose(b, a, rtol=2e-5, atol=1e-6)
print("   restored model + normalizer reproduce producer outputs exactly")

# --- 2. designed tensor parallelism ----------------------------------------
print("== 2. Megatron tensor parallelism (dp x tp mesh)")

n_dev = len(jax.devices())
if n_dev >= 4:
    from deeplearning4j_tpu.nn.graph import ComputationGraph
    from deeplearning4j_tpu.parallel import make_mesh
    from deeplearning4j_tpu.parallel.sharding import shard_model
    from deeplearning4j_tpu.zoo.models import TransformerEncoder

    tp = 4 if n_dev % 4 == 0 else 2
    dp = n_dev // tp
    mesh = make_mesh({"data": dp, "model": tp}, jax.devices()[:dp * tp])

    def enc():
        return ComputationGraph(TransformerEncoder(
            num_labels=4, vocab_size=64, max_length=8, n_layers=1,
            d_model=8 * tp, n_heads=tp, d_ff=16 * tp, seed=7).conf()).init()

    replicated, sharded = enc(), enc()
    shard_model(sharded, mesh, tp_axis="model")  # QKV column / Wo row,
    # ff1 column / ff2 row — one all-reduce per pair, no all-gathers
    toks = rng.integers(0, 64, size=(2 * dp, 8)).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(sharded.output_single(toks)),
        np.asarray(replicated.output_single(toks)), rtol=2e-4, atol=1e-5)
    print(f"   TP TransformerEncoder on {dp}x{tp} mesh == replicated")
else:
    print(f"   skipped ({n_dev} devices; run with the 8-device CPU mesh)")

# --- 3. Chinese lattice segmentation ---------------------------------------
print("== 3. Chinese lattice Viterbi vs greedy longest-match")

from deeplearning4j_tpu.nlp.dictionary_tokenizer import (
    derive_dictionary_from_tagged_corpus,
    greedy_segment,
    viterbi_segment,
)

zh_corpus = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "fixtures", "zh_tagged_corpus.tsv")
zh = derive_dictionary_from_tagged_corpus(zh_corpus)
trap = "他研究生命的起源。"
print("   viterbi:", "|".join(e.surface for e in viterbi_segment(trap, zh)))
print("   greedy :", "|".join(greedy_segment(trap, zh)),
      "   <- falls into the 研究生 trap")

# --- 4. typed unknown words ------------------------------------------------
print("== 4. kuromoji-style unknown-word handling")

ja_corpus = os.path.join(os.path.dirname(zh_corpus),
                         "ja_tagged_corpus.tsv")
ja = derive_dictionary_from_tagged_corpus(ja_corpus)
for e in viterbi_segment("私はテレビゲームとABC123を学ぶ", ja):
    tag = f"  ({e.features[1]})" if e.features[:1] == ("UNK",) else ""
    print(f"   {e.surface}{tag}")

print("round-5 tour complete")
