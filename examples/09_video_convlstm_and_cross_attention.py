"""Image-sequence (video) models: ConvLSTM2D and cross-attention.

Two newer capabilities on top of the reference's layer set:
- ConvLSTM2D classifies a synthetic "moving blob" video by motion direction
  (the conv gates see [N, T, H, W, C] directly).
- A cross-attention graph attends from a query sequence over a longer
  key/value sequence (the encoder-decoder attention pattern) using the
  multi-input layer protocol.

Run: python examples/09_video_convlstm_and_cross_attention.py  (CPU-friendly)
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import (
    ConvLSTM2DLayer,
    CrossAttentionLayer,
    LastTimeStepWrapper,
    LossLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def moving_blob_video(n=384, t=5, hw=8, seed=0):
    """Class 0: blob sweeps top→bottom; class 1: bottom→top."""
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 0.2, size=(n, t, hw, hw, 1)).astype(np.float32)
    cls = rng.integers(0, 2, n)
    for i in range(n):
        for step in range(t):
            row = step if cls[i] == 0 else t - 1 - step
            x[i, step, row, :, 0] += 2.0
    return x, np.eye(2, dtype=np.float32)[cls], cls


def convlstm_demo():
    x, y, cls = moving_blob_video()
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(3e-3)).list()
            .layer(LastTimeStepWrapper(layer=ConvLSTM2DLayer(
                n_out=8, kernel_size=(3, 3), convolution_mode="same")))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.recurrent_convolutional(8, 8, 1, 5))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ListDataSetIterator(DataSet(x, y), 64, shuffle=True), epochs=6)
    ev = net.evaluate(ListDataSetIterator(DataSet(x, y), 128))
    print(f"ConvLSTM2D motion-direction accuracy: {ev.accuracy():.3f}")


def cross_attention_demo():
    rng = np.random.default_rng(2)
    # pointer task: each memory row carries a positional one-hot (dims 0:9)
    # plus a random payload (dims 9:12); each query step points at one
    # position. The layer must learn to route the pointed-at payload — pure
    # content-based cross-attention, learnable to near-zero loss.
    n, tq, tm = 128, 4, 9
    mem = np.zeros((n, tm, 12), np.float32)
    mem[:, :, 9:] = rng.normal(size=(n, tm, 3)).astype(np.float32)
    mem[:, np.arange(tm), np.arange(tm)] = 1.0
    idx = rng.integers(0, tm, size=(n, tq))
    q = np.zeros((n, tq, 12), np.float32)
    for i in range(n):
        q[i, np.arange(tq), idx[i]] = 1.0
    tgt = np.take_along_axis(mem, idx[:, :, None], axis=1)  # pointed rows

    g = (NeuralNetConfiguration.builder().seed(3).updater(Adam(1e-2))
         .graph_builder()
         .add_inputs("query", "memory")
         .set_input_types(InputType.recurrent(12, tq), InputType.recurrent(12, tm)))
    g.add_layer("xatt", CrossAttentionLayer(n_heads=2, head_size=6),
                "query", "memory")
    g.add_layer("out", LossLayer(loss="mse", activation="identity"), "xatt")
    g.set_outputs("out")
    net = ComputationGraph(g.build()).init()
    for _ in range(300):
        net.fit([q, mem], [tgt])
    print(f"cross-attention pointer-task loss: {net.score_:.4f}")
    assert net.score_ < 0.05


if __name__ == "__main__":
    convlstm_demo()
    cross_attention_demo()
