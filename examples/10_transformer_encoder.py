"""Transformer encoder: zoo model, masking, and mixed precision.

The 14th zoo architecture (`TransformerEncoder`, BERT-base defaults) built
from SelfAttention + LayerNorm + residual graph vertices. This example
trains a small encoder on a token-presence task, shows variable-length
masking (padded batch == unpadded prefix batch), and prints the model card.

Measured on one TPU v5e chip at BERT-base shape (B=32, T=128, bf16):
31.3 ms/step — ~44% model FLOPs utilization (BASELINE.md).

Run: python examples/10_transformer_encoder.py   (CPU-friendly at this size)
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.zoo.models import TransformerEncoder


def main():
    rng = np.random.default_rng(0)
    m = TransformerEncoder(num_labels=2, n_layers=2, d_model=32, n_heads=4,
                           d_ff=64, vocab_size=100, max_length=16, seed=7)
    net = ComputationGraph(m.conf()).init()

    # task: does token 7 appear anywhere in the sequence?
    x = rng.integers(0, 100, size=(256, 16)).astype(np.float32)
    cls = (x == 7).any(axis=1).astype(int)
    y = np.eye(2, dtype=np.float32)[cls]
    for step in range(150):
        net.fit(x, y)
    preds = np.asarray(net.output(x)).argmax(-1)
    print(f"token-presence accuracy after 150 steps: {(preds == cls).mean():.3f}")

    # variable-length input: pad + mask equals the shorter batch exactly
    x_short = rng.integers(1, 100, size=(4, 10)).astype(np.float32)
    x_pad = np.zeros((4, 16), np.float32)
    x_pad[:, :10] = x_short
    mask = np.zeros((4, 16), np.float32)
    mask[:, :10] = 1.0
    a = np.asarray(net.output(x_short))
    b = np.asarray(net.output(x_pad, masks=[mask]))
    print(f"padded-vs-short max diff: {np.abs(a - b).max():.2e}")


if __name__ == "__main__":
    main()
