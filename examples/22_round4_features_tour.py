"""Example 22 — round-4 feature tour: exact distributed resume, dropout
schedules, pretrained transport, scatter ops.

Four additions in one runnable script:

1. EXACT preemption resume of threshold-compressed distributed training —
   model checkpoint (orbax) + the master's compression state
   (``save_state``/``load_state``: adaptive threshold + residual shards);
   resumed params equal the uninterrupted run bit-for-bit.
2. Dropout schedules (``Dropout.java:45`` pSchedule): the retain
   probability follows the device tick inside the compiled step.
3. Zoo pretrained transport over file:// — registered URL, fetch,
   Adler32 verify, cache.
4. SameDiff scatter/segment ops in a trained graph.

Run: PYTHONPATH=/root/repo:/root/.axon_site python examples/22_round4_features_tour.py
"""

import os
import tempfile
import zlib

import jax

jax.config.update("jax_platforms", "cpu")  # small demo; skip the TPU tunnel

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.dropout import Dropout
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, MapSchedule
from deeplearning4j_tpu.parallel import (
    DistributedMultiLayerNetwork,
    SharedTrainingMaster,
)
from deeplearning4j_tpu.parallel.mesh import make_mesh
from deeplearning4j_tpu.util.orbax_checkpoint import OrbaxCheckpointManager

# --- 1. exact resume of compressed distributed training --------------------
print("== 1. exact distributed resume (model + compression state)")


def build_net(seed=3):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(0.01))
            .list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


rng = np.random.default_rng(0)
x = rng.normal(size=(128, 6)).astype(np.float32)
y = np.eye(3, dtype=np.float32)[rng.integers(0, 3, 128)]
ds = DataSet(x, y)
mesh = make_mesh()  # all local devices on the data axis

net_a = build_net()
m_a = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3, mesh=mesh)
front_a = DistributedMultiLayerNetwork(net_a, m_a)
for _ in range(6):
    front_a.fit([ds])

net_b = build_net()
m_b = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3, mesh=mesh)
front_b = DistributedMultiLayerNetwork(net_b, m_b)
for _ in range(3):
    front_b.fit([ds])
with tempfile.TemporaryDirectory() as td:
    with OrbaxCheckpointManager(os.path.join(td, "ckpt")) as mgr:
        mgr.save(3, net_b)
        mgr.wait_until_finished()
    m_b.save_state(os.path.join(td, "master.npz"))
    # ---- "the job is preempted here; a new process restarts" ----
    with OrbaxCheckpointManager(os.path.join(td, "ckpt")) as mgr:
        resumed = mgr.restore()
    m_c = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                               mesh=mesh)
    m_c.load_state(os.path.join(td, "master.npz"))
    front_c = DistributedMultiLayerNetwork(resumed, m_c)
    for _ in range(3):
        front_c.fit([ds])
drift = max(float(np.abs(np.asarray(pa[k]) - np.asarray(pc[k])).max())
            for pa, pc in zip(net_a.params, resumed.params) for k in pa)
print(f"   resumed-vs-uninterrupted max param drift: {drift:.2e}")
assert drift < 1e-5

# --- 2. dropout schedules ---------------------------------------------------
print("== 2. dropout pSchedule follows the device tick")
sched = MapSchedule(values=((0, 0.95), (10, 0.6)))
conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3)).list()
        .layer(DenseLayer(n_in=6, n_out=16, activation="relu",
                          dropout=Dropout(sched)))
        .layer(OutputLayer(n_in=16, n_out=3))
        .build())
snet = MultiLayerNetwork(conf).init()
for i in range(15):
    snet.fit(x, y)
print(f"   trained 15 steps across the schedule breakpoint; "
      f"score={float(snet.score_):.4f}")

# --- 3. pretrained transport over file:// ----------------------------------
print("== 3. zoo pretrained transport (fetch -> checksum -> cache)")
from deeplearning4j_tpu.util.model_serializer import write_model
from deeplearning4j_tpu.zoo.models import SimpleCNN
from deeplearning4j_tpu.zoo.zoo_model import PretrainedType

with tempfile.TemporaryDirectory() as td:
    src = SimpleCNN(num_labels=3, input_shape=(3, 32, 32)).init()
    blob = os.path.join(td, "weights.zip")
    write_model(src, blob)
    with open(blob, "rb") as fh:
        checksum = zlib.adler32(fh.read())
    os.environ["DL4J_TPU_ZOO_DIR"] = os.path.join(td, "cache")
    SimpleCNN.PRETRAINED_URLS = {PretrainedType.CIFAR10: "file://" + blob}
    SimpleCNN.PRETRAINED_CHECKSUMS = {PretrainedType.CIFAR10: checksum}
    fetched = SimpleCNN(num_labels=3, input_shape=(3, 32, 32)) \
        .init_pretrained(PretrainedType.CIFAR10)
    xi = rng.normal(size=(2, 32, 32, 3)).astype(np.float32)
    same = np.allclose(np.asarray(fetched.output(xi)),
                       np.asarray(src.output(xi)), rtol=1e-5)
    print(f"   fetched+verified weights reproduce source outputs: {same}")
    assert same

# --- 4. SameDiff scatter/segment ops ---------------------------------------
print("== 4. SameDiff scatter_add + segment_sum in a trained graph")
from deeplearning4j_tpu.autodiff import SameDiff, TrainingConfig

sd = SameDiff.create()
xin = sd.place_holder("input", shape=(None, 6))
lab = sd.place_holder("label", shape=(None, 2))
w = sd.var("w", value=(rng.normal(size=(6, 2)) * 0.1))
base = sd.constant("base", np.zeros((4, 2), np.float32))
idx = sd.constant("idx", np.array([1, 3], np.int32))
upd = sd.var("upd", value=np.zeros((2, 2)))
sd.math.scatter_add(base, idx, upd, name="table")  # trainable lookup rows
logits = xin.mmul(w, name="logits")
sd.loss.softmax_cross_entropy(lab, logits, name="loss")
sd.set_loss_variables("loss")
sd.set_training_config(TrainingConfig(
    updater=Adam(0.05), data_set_feature_mapping=["input"],
    data_set_label_mapping=["label"]))
cls2 = (x[:, 0] > 0).astype(int)
loss = sd.fit(DataSet(x, np.eye(2, dtype=np.float32)[cls2]), epochs=60)
print(f"   samediff graph trained to loss {float(loss):.4f}")
assert float(loss) < 0.4

print("example 22 complete")
