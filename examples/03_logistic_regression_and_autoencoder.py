"""Logistic regression + autoencoder anomaly detection.

Mirrors tutorials "03. Logistic Regression" and "05. Basic Autoencoder —
anomaly detection using reconstruction error".

Run: python examples/03_logistic_regression_and_autoencoder.py
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import AutoEncoderLayer, DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def logistic_regression():
    rng = np.random.default_rng(0)
    y = rng.integers(0, 2, 400)
    x = rng.normal(size=(400, 4)).astype(np.float32) + y[:, None] * 1.5
    ds = DataSet(x, np.eye(2, dtype=np.float32)[y])
    # logistic regression == a single softmax output layer
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.05)).list()
            .layer(OutputLayer(n_out=2, activation="softmax",
                               loss="negativeloglikelihood"))
            .set_input_type(InputType.feed_forward(4)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ListDataSetIterator(ds, 64, shuffle=True), epochs=15)
    print("logistic regression accuracy:",
          net.evaluate(ListDataSetIterator(ds, 256)).accuracy())


def autoencoder_anomaly():
    rng = np.random.default_rng(1)
    normal = rng.normal(0, 0.5, size=(500, 16)).astype(np.float32)
    anomalies = rng.uniform(-4, 4, size=(25, 16)).astype(np.float32)
    ds = DataSet(normal, normal)  # reconstruct the input
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3)).list()
            .layer(AutoEncoderLayer(n_out=8, activation="tanh"))
            .layer(OutputLayer(n_out=16, activation="identity", loss="mse"))
            .set_input_type(InputType.feed_forward(16)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(ListDataSetIterator(ds, 64, shuffle=True), epochs=30)

    def recon_error(batch):
        out = np.asarray(net.output(batch))
        return np.mean((out - batch) ** 2, axis=1)

    print("mean reconstruction error — normal: %.4f, anomalies: %.4f"
          % (recon_error(normal).mean(), recon_error(anomalies).mean()))


if __name__ == "__main__":
    logistic_regression()
    autoencoder_anomaly()
