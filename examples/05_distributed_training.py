"""Distributed training over a device mesh.

Mirrors the reference's scale-out stack (ParallelWrapper, Spark training
masters): the same model trained three ways — per-step synchronous data
parallelism, periodic parameter averaging, and threshold-compressed gradient
sharing — on a virtual 8-device CPU mesh (exactly how multi-chip sharding is
validated without hardware; on a real pod the same code rides ICI).

Run: XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
     python examples/05_distributed_training.py
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.parallel import (
    DistributedMultiLayerNetwork,
    ParallelWrapper,
    ParameterAveragingTrainingMaster,
    SharedTrainingMaster,
)
from deeplearning4j_tpu.parallel.mesh import make_mesh


def make_net():
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Sgd(0.1)).list()
            .layer(DenseLayer(n_out=16, activation="tanh"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(6)).build())
    return MultiLayerNetwork(conf).init()


def main():
    rng = np.random.default_rng(0)
    y = rng.integers(0, 3, 512)
    x = rng.normal(size=(512, 6)).astype(np.float32)
    x[np.arange(512), y] += 2.5
    ds = DataSet(x, np.eye(3, dtype=np.float32)[y])
    mesh = make_mesh({"data": 8})
    print("mesh:", dict(mesh.shape))

    # 1. per-step sync DP: batch sharded, params replicated, XLA emits the
    #    gradient all-reduce
    net = make_net()
    ParallelWrapper(net, mesh, mode="shared_gradients").fit(
        ListDataSetIterator(ds, 128, shuffle=True), epochs=10)
    print("shared_gradients accuracy:",
          net.evaluate(ListDataSetIterator(ds, 256)).accuracy())

    # 2. parameter averaging every 4 local steps (Spark TrainingMaster role)
    net = make_net()
    master = ParameterAveragingTrainingMaster(batch_size_per_worker=16,
                                              averaging_frequency=4, mesh=mesh)
    DistributedMultiLayerNetwork(net, master).fit([ds], epochs=10)
    print("parameter averaging accuracy:",
          net.evaluate(ListDataSetIterator(ds, 256)).accuracy(),
          "| phase stats:", master.get_training_stats().as_dict())

    # 3. threshold-compressed gradient sharing (Aeron/Strom design, on-mesh)
    net = make_net()
    master = SharedTrainingMaster(batch_size_per_worker=16, threshold=1e-3,
                                  mesh=mesh)
    front = DistributedMultiLayerNetwork(net, master)
    front.fit(ListDataSetIterator(ds, 128, shuffle=True), epochs=15)
    print("shared (compressed) accuracy:",
          net.evaluate(ListDataSetIterator(ds, 256)).accuracy(),
          f"| final threshold {master.threshold:.2e}")


if __name__ == "__main__":
    main()
