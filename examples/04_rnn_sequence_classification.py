"""RNN sequence classification with masking + early stopping.

Mirrors tutorials "08. RNNs — Sequence Classification" / "12. Clinical Time
Series LSTM" / "09. Early Stopping": variable-length sequences (padding +
masks), an LSTM classifier read at the last step, early stopping on a
held-out score.

Run: python examples/04_rnn_sequence_classification.py
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import LSTMLayer, RnnOutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.optimize.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    InMemoryModelSaver,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)


def make_sequences(n=256, t_max=20, seed=0):
    """Class 0: rising ramps; class 1: flat noise. Variable lengths."""
    rng = np.random.default_rng(seed)
    x = np.zeros((n, t_max, 1), np.float32)
    y = np.zeros((n, t_max, 2), np.float32)
    fm = np.zeros((n, t_max), np.float32)
    for i in range(n):
        t = int(rng.integers(8, t_max + 1))
        cls = i % 2
        sig = (np.linspace(0, 1, t) if cls == 0
               else np.zeros(t)) + rng.normal(0, 0.1, t)
        x[i, :t, 0] = sig
        fm[i, :t] = 1.0
        y[i, t - 1, cls] = 1.0  # label at the last real step
    lm = (y.sum(-1) > 0).astype(np.float32)
    return DataSet(x, y, fm, lm)


def main():
    train = make_sequences(seed=0)
    valid = make_sequences(n=128, seed=9)
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(5e-3)).list()
            .layer(LSTMLayer(n_out=16, activation="tanh"))
            .layer(RnnOutputLayer(n_out=2, activation="softmax",
                                  loss="negativeloglikelihood"))
            .set_input_type(InputType.recurrent(1)).build())
    net = MultiLayerNetwork(conf).init()

    es = EarlyStoppingConfiguration(
        epoch_termination_conditions=[
            MaxEpochsTerminationCondition(30),
            ScoreImprovementEpochTerminationCondition(5)],
        score_calculator=DataSetLossCalculator(ListDataSetIterator(valid, 64)),
        model_saver=InMemoryModelSaver())
    result = EarlyStoppingTrainer(es, net,
                                  ListDataSetIterator(train, 64, shuffle=True)).fit()
    print(f"stopped at epoch {result.total_epochs} "
          f"(best epoch {result.best_model_epoch}, "
          f"best score {result.best_model_score:.4f})")
    ev = result.best_model.evaluate(ListDataSetIterator(valid, 128))
    print("validation accuracy:", ev.accuracy())


if __name__ == "__main__":
    main()
