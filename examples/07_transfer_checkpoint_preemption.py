"""Transfer learning, checkpointing, preemption recovery, sklearn pipeline.

Mirrors the transfer-learning / model-persistence tutorials plus two
TPU-specific additions: the preemption checkpoint handler and the
scikit-learn estimator adapter (the Spark ML pipeline role).

Run: python examples/07_transfer_checkpoint_preemption.py
"""

import os
import signal
import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.transferlearning import TransferLearning
from deeplearning4j_tpu.sklearn_adapter import SklearnDl4jClassifier
from deeplearning4j_tpu.util import model_serializer
from deeplearning4j_tpu.util.preemption import PreemptionHandler


def make_data(n=256, n_classes=3, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, n)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    x[np.arange(n), y] += 2.5
    return x, y, DataSet(x, np.eye(n_classes, dtype=np.float32)[y])


def main():
    _, _, ds = make_data()
    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(8)).build())
    base = MultiLayerNetwork(conf).init()
    base.fit(ListDataSetIterator(ds, 64, shuffle=True), epochs=10)

    # --- checkpoint round trip -----------------------------------------
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.zip")
        model_serializer.write_model(base, path)
        restored = model_serializer.restore_multi_layer_network(path)
        print("restored accuracy:",
              restored.evaluate(ListDataSetIterator(ds, 256)).accuracy())

        # --- preemption: SIGTERM mid-training saves + resumes ----------
        ckpt = os.path.join(d, "preempt.zip")
        handler = PreemptionHandler(base, ckpt).arm()
        os.kill(os.getpid(), signal.SIGTERM)  # simulate a maintenance event
        handler.disarm()
        resumed, state = PreemptionHandler.resume(ckpt)
        print("resumed at iteration", state["iteration"],
              "epoch", state["epoch"])

    # --- transfer learning: freeze features, new 2-class head -----------
    _, _, ds2 = make_data(n_classes=2, seed=5)
    transferred = (TransferLearning.Builder(base)
                   .set_feature_extractor(1)  # freeze layers 0..1
                   .remove_output_layer()
                   .add_layer(OutputLayer(n_out=2))
                   .build())
    transferred.fit(ListDataSetIterator(ds2, 64, shuffle=True), epochs=10)
    print("transferred (2-class) accuracy:",
          transferred.evaluate(ListDataSetIterator(ds2, 256)).accuracy())

    # --- sklearn estimator (Spark-ML-glue role) ------------------------
    x, y, _ = make_data(seed=9)

    def conf_factory(n_in, n_out):
        return (NeuralNetConfiguration.builder().seed(0).list()
                .layer(DenseLayer(n_out=16, activation="relu"))
                .layer(OutputLayer(n_out=n_out))
                .set_input_type(InputType.feed_forward(n_in)).build())

    clf = SklearnDl4jClassifier(conf_factory, epochs=10, batch_size=64)
    clf.fit(x, y)
    print("sklearn-style classifier score:", clf.score(x, y))


if __name__ == "__main__":
    main()
