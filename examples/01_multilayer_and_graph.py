"""MultiLayerNetwork and ComputationGraph basics.

Mirrors tutorial "01. MultiLayerNetwork and ComputationGraph": build the same
classifier twice — as a sequential net and as a DAG — train, evaluate.

Run: python examples/01_multilayer_and_graph.py   (CPU-friendly)
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def make_data(n=512, seed=0):
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 3, n)
    x = rng.normal(size=(n, 8)).astype(np.float32)
    x[np.arange(n), y] += 2.5
    return DataSet(x, np.eye(3, dtype=np.float32)[y])


def main():
    ds = make_data()
    it = ListDataSetIterator(ds, 64, shuffle=True)

    # --- sequential (MultiLayerNetwork) ---------------------------------
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3)).list()
            .layer(DenseLayer(n_out=32, activation="relu"))
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax",
                               loss="negativeloglikelihood"))
            .set_input_type(InputType.feed_forward(8))
            .build())
    mln = MultiLayerNetwork(conf).init()
    mln.fit(it, epochs=10)
    print("MultiLayerNetwork accuracy:",
          mln.evaluate(ListDataSetIterator(ds, 256)).accuracy())

    # --- DAG (ComputationGraph): two towers merged ----------------------
    g = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-3))
         .graph_builder()
         .add_inputs("in")
         .set_input_types(InputType.feed_forward(8)))
    from deeplearning4j_tpu.nn.vertices import MergeVertex
    g.add_layer("towerA", DenseLayer(n_out=16, activation="relu"), "in")
    g.add_layer("towerB", DenseLayer(n_out=16, activation="tanh"), "in")
    g.add_vertex("merge", MergeVertex(), "towerA", "towerB")
    g.add_layer("out", OutputLayer(n_out=3), "merge")
    cg = ComputationGraph(g.set_outputs("out").build())
    cg.init()
    cg.fit(it, epochs=10)
    print("ComputationGraph accuracy:",
          cg.evaluate(ListDataSetIterator(ds, 256)).accuracy())


if __name__ == "__main__":
    main()
