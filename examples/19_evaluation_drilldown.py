"""Evaluation depth: top-N accuracy, per-record error drilldown, binned ROC.

The reference's full evaluation workflow (``Evaluation.java:144`` top-N
constructor, ``:1506`` getPredictionErrors with RecordMetaData,
``ROC.java:61-85`` thresholded mode for distributed eval): train a small
classifier from a CSV through ``RecordReaderDataSetIterator`` with metadata
collection, evaluate with top-2 accuracy, trace every misclassification back
to its source record, and merge sharded binned-ROC evaluations.

Run: python examples/19_evaluation_drilldown.py   (CPU-friendly, <1 min)
"""

import os
import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.records import (
    CSVRecordReader,
    RecordReaderDataSetIterator,
)
from deeplearning4j_tpu.eval.roc import ROC
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam


def write_csv(path, n=240, seed=5):
    rng = np.random.default_rng(seed)
    rows = []
    for i in range(n):
        cls = i % 3
        f = rng.normal(0, 0.45, 4)  # noisy on purpose: we WANT errors
        f[cls] += 1.6
        rows.append(",".join(f"{v:.5f}" for v in f) + f",{cls}")
    with open(path, "w") as fh:
        fh.write("\n".join(rows))


def main():
    with tempfile.TemporaryDirectory() as d:
        csv = os.path.join(d, "train.csv")
        write_csv(csv)

        conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(0.02))
                .list()
                .layer(DenseLayer(n_in=4, n_out=16, activation="relu"))
                .layer(OutputLayer(n_in=16, n_out=3))
                .build())
        net = MultiLayerNetwork(conf).init()
        train_it = RecordReaderDataSetIterator(
            CSVRecordReader(csv), 32, label_index=4, num_possible_labels=3)
        for _ in range(10):
            net.fit(train_it)

        # ---- top-N accuracy + metadata-backed drilldown -----------------
        eval_it = RecordReaderDataSetIterator(
            CSVRecordReader(csv), 32, label_index=4, num_possible_labels=3,
            collect_meta_data=True)
        e = net.evaluate(eval_it, top_n=2)
        print(f"accuracy {e.accuracy():.3f}  top-2 {e.top_n_accuracy():.3f}  "
              f"F1 {e.f1():.3f}")

        errors = e.get_prediction_errors()
        print(f"{len(errors)} misclassified records:")
        for p in errors[:5]:
            print(f"  true {p.actual} -> predicted {p.predicted}  "
                  f"from {p.record_meta_data.get_location()}")
        # reload the original records behind the first few errors
        reloaded = eval_it.load_from_meta_data(
            [p.record_meta_data for p in errors[:3]])
        print("first offending source record:",
              [round(float(v), 3) for v in
               np.asarray(reloaded.features)[0]])

        # ---- binned ROC: shard, evaluate independently, merge -----------
        it2 = RecordReaderDataSetIterator(
            CSVRecordReader(csv), 240, label_index=4, num_possible_labels=3)
        ds = next(iter(it2))
        probs = np.asarray(net.output(np.asarray(ds.features)))
        y = np.asarray(ds.labels)
        scores0 = probs[:, 0]  # one-vs-all, class 0
        labels0 = y[:, 0]
        shards = []
        for k in range(4):  # four "workers", O(steps) state each
            r = ROC(threshold_steps=100)
            r.eval(labels0[k * 60:(k + 1) * 60], scores0[k * 60:(k + 1) * 60])
            shards.append(r)
        merged = shards[0]
        for r in shards[1:]:
            merged.merge(r)
        exact = ROC()
        exact.eval(labels0, scores0)
        print(f"class-0 AUC: merged-binned {merged.calculate_auc():.4f}  "
              f"exact {exact.calculate_auc():.4f}")
        print("binned state is O(threshold_steps) and JSON-serializable:",
              len(merged.to_json()), "bytes")


if __name__ == "__main__":
    main()
