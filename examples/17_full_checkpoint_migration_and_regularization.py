"""Full DL4J checkpoint migration + the round-2 regularization family.

1. FULL checkpoint restore: a ModelSerializer zip with ND4J-binary
   ``coefficients.bin`` + ``updaterState.bin`` comes back as a ready-to-serve
   network — parameters, BN running stats, and Adam state included
   (``ModelSerializer.restoreMultiLayerNetwork:182`` /
   ``restoreComputationGraph:389`` parity; tests/fixtures carries the zips).
2. Serve and fine-tune the restored net (the "half a migration" gap from the
   round-1 verdict, closed).
3. Train with the regularization family the reference configures through
   ``nn/conf/``: parameter constraints (MaxNorm post-update projection),
   DropConnect weight noise, and AlphaDropout — all inside the one jitted
   train step.
4. Dictionary-backed tokenization: the MeCab-format lattice Viterbi
   segmenter behind the TokenizerFactory SPI feeding Word2Vec.

Run: python examples/17_full_checkpoint_migration_and_regularization.py
"""

import os

import numpy as np

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        os.pardir, "tests", "fixtures")


def restore_and_finetune():
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.modelimport.dl4j import restore_multi_layer_network

    zip_path = os.path.join(FIXTURES, "dl4j_checkpoint_convnet.zip")
    net = restore_multi_layer_network(zip_path)
    print("restored conv net:",
          sum(int(np.prod(v.shape)) for p in net.params for v in p.values()),
          "params; Adam state slots:",
          sorted(net.updater_states[0]["W"]))

    # serve: outputs match the activations recorded when the zip was written
    exp = np.load(os.path.join(FIXTURES,
                               "dl4j_checkpoint_convnet_expected.npz"))
    out = np.asarray(net.output(exp["x"]))
    print("serving drift vs recorded activations:",
          float(np.abs(out - exp["out"]).max()))

    # fine-tune: training continues from the checkpoint's updater state
    rng = np.random.default_rng(0)
    cls = rng.integers(0, 3, 64)
    x = rng.normal(size=(64, 8, 8, 1)).astype(np.float32)
    x[np.arange(64), 1 + cls] += 2.0
    y = np.eye(3, dtype=np.float32)[cls]
    s0 = net.score(DataSet(x, y))
    net.fit(ListDataSetIterator(DataSet(x, y), 32, shuffle=True), epochs=20)
    print(f"fine-tune: score {s0:.4f} -> {float(net.score_):.4f}")


def regularization_family():
    from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
    from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
    from deeplearning4j_tpu.nn.constraints import (MaxNormConstraint,
                                                   NonNegativeConstraint)
    from deeplearning4j_tpu.nn.dropout import AlphaDropout
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    from deeplearning4j_tpu.nn.updaters import Adam
    from deeplearning4j_tpu.nn.weightnoise import DropConnect

    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(5e-3))
            .constrain_weights(MaxNormConstraint(max_norm=2.0))
            .constrain_bias(NonNegativeConstraint())
            .weight_noise(DropConnect(p=0.95))
            .list()
            .layer(DenseLayer(n_out=32, activation="selu",
                              dropout=AlphaDropout(p=0.9)))
            .layer(OutputLayer(n_out=3))
            .set_input_type(InputType.feed_forward(10)).build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.default_rng(0)
    cls = rng.integers(0, 3, 512)
    x = rng.normal(0, 0.3, size=(512, 10)).astype(np.float32)
    x[np.arange(512), cls] += 2.0
    y = np.eye(3, dtype=np.float32)[cls]
    net.fit(ListDataSetIterator(DataSet(x, y), 128, shuffle=True), epochs=15)
    w = np.asarray(net.params[0]["W"])
    print("constraints held: max col norm",
          round(float(np.sqrt((w ** 2).sum(0)).max()), 3),
          "<= 2.0; min bias", float(np.asarray(net.params[0]["b"]).min()),
          ">= 0; accuracy",
          net.evaluate(ListDataSetIterator(DataSet(x, y), 256)).accuracy())


def dictionary_tokenization():
    from deeplearning4j_tpu.nlp import DictionaryTokenizerFactory, Word2Vec

    fac = DictionaryTokenizerFactory.from_path(
        os.path.join(FIXTURES, "mini_ja_dict"))
    print("lattice segmentation:",
          fac.create("すもももももももものうち").get_tokens())
    w2v = (Word2Vec.Builder().min_word_frequency(1).layer_size(16).seed(1)
           .epochs(2).tokenizer_factory(fac)
           .iterate(["すもももももももものうち"] * 50).build())
    w2v.fit()
    print("embedding for すもも:", w2v.get_word_vector("すもも")[:4], "…")


def reverse_migration():
    """Hand a model trained HERE back to a DL4J deployment: export as a
    ModelSerializer zip (config dialect + coefficients.bin +
    updaterState.bin) and prove the round trip."""
    import tempfile

    import numpy as np

    from deeplearning4j_tpu.modelimport.dl4j import restore_multi_layer_network
    from deeplearning4j_tpu.modelimport.dl4j_export import (
        export_multi_layer_network,
    )
    from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
    from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

    conf = (NeuralNetConfiguration.builder().seed(3).updater("adam").list()
            .layer(DenseLayer(n_in=4, n_out=8, activation="tanh"))
            .layer(OutputLayer(n_in=8, n_out=2))
            .build())
    net = MultiLayerNetwork(conf).init()
    rng = np.random.RandomState(0)
    x = rng.randn(32, 4).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.randint(0, 2, 32)]
    for _ in range(5):
        net.fit(x, y)
    with tempfile.TemporaryDirectory() as d:
        path = f"{d}/handback.zip"
        export_multi_layer_network(net, path)
        back = restore_multi_layer_network(path)
        back.fit(x, y)  # Adam moments travelled: fine-tuning continues
        net.fit(x, y)
        diff = float(np.abs(np.asarray(net.output(x))
                            - np.asarray(back.output(x))).max())
        print(f"reverse migration: resumed-training output diff {diff:.2e}")


def main():
    restore_and_finetune()
    regularization_family()
    dictionary_tokenization()
    reverse_migration()


if __name__ == "__main__":
    main()
