"""Anomaly detection, center-loss embeddings, and hyperparameter search.

Three reference tutorial topics (`dl4j-examples/tutorials` 05, 07, 11) on
the TPU-native stack:

1. **Autoencoder anomaly detection** — train an `AutoEncoderLayer` on
   "normal" data only; anomalies score much higher reconstruction error
   (tutorial 05's MNIST ranking, on synthetic structured data);
2. **Center loss** — `CenterLossOutputLayer` pulls same-class embeddings
   toward learned centers (tutorial 07's FaceNet recipe): intra-class
   spread shrinks vs a plain softmax head;
3. **Hyperparameter search** — `optimize/hpo.py` (the Arbiter role:
   parameter spaces + RandomSearch) driven by
   `EarlyStoppingTrainer` with held-out scoring picks width/learning-rate
   (tutorial 11 uses Arbiter, an external dependency of the reference; the
   search loop here is plain Python over the same config builder).

Run: python examples/15_anomaly_centerloss_hpo.py   (CPU-friendly)
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import (
    AutoEncoderLayer,
    CenterLossOutputLayer,
    DenseLayer,
    OutputLayer,
)
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.optimize.earlystopping import (
    DataSetLossCalculator,
    EarlyStoppingConfiguration,
    EarlyStoppingTrainer,
    MaxEpochsTerminationCondition,
    ScoreImprovementEpochTerminationCondition,
)

DIM = 24


def structured(rng, n):
    """'Normal' samples live on a 4-D latent plane embedded in DIM dims."""
    basis = np.linalg.qr(np.random.default_rng(99).normal(size=(DIM, 4)))[0]
    return (rng.normal(size=(n, 4)) @ basis.T).astype(np.float32)


def main():
    rng = np.random.default_rng(0)

    # -- 1. anomaly detection by reconstruction error ------------------------
    x_norm = structured(rng, 512)
    conf = (NeuralNetConfiguration.builder().seed(1).updater(Adam(1e-2))
            .list()
            .layer(AutoEncoderLayer(n_out=4, corruption_level=0.0,
                                    activation="tanh"))
            .layer(OutputLayer(n_out=DIM, activation="identity", loss="mse"))
            .set_input_type(InputType.feed_forward(DIM))
            .build())
    ae = MultiLayerNetwork(conf).init()
    it = ListDataSetIterator(DataSet(x_norm, x_norm), 64, shuffle=True)
    ae.fit(it, epochs=40)

    def recon_error(batch):
        out = np.asarray(ae.output(batch))
        return ((out - batch) ** 2).mean(axis=1)

    normal_scores = recon_error(structured(rng, 128))       # held-out normal
    anomaly_scores = recon_error(
        rng.normal(size=(128, DIM)).astype(np.float32))     # off-manifold
    thresh = np.quantile(normal_scores, 0.95)
    tpr = (anomaly_scores > thresh).mean()
    print(f"anomaly detection: 95%-normal threshold {thresh:.4f}, "
          f"anomaly detection rate {tpr:.2f}")

    # -- 2. center loss tightens the embedding space -------------------------
    y_idx = rng.integers(0, 3, 384)
    xc = rng.normal(size=(384, 8)).astype(np.float32)
    xc[np.arange(384), y_idx] += 2.0
    yc = np.eye(3, dtype=np.float32)[y_idx]

    def intra_class_spread(lambda_):
        conf = (NeuralNetConfiguration.builder().seed(3).updater(Adam(5e-3))
                .list()
                .layer(DenseLayer(n_in=8, n_out=6, activation="tanh"))
                .layer(CenterLossOutputLayer(n_out=3, lambda_=lambda_))
                .set_input_type(InputType.feed_forward(8))
                .build())
        net = MultiLayerNetwork(conf).init()
        for _ in range(120):
            net.fit(xc, yc)
        emb = np.asarray(net.feed_forward(xc)[1])  # activations after layer 0
        return np.mean([np.linalg.norm(
            emb[y_idx == c] - emb[y_idx == c].mean(0), axis=1).mean()
            for c in range(3)])

    plain, center = intra_class_spread(0.0), intra_class_spread(0.5)
    print(f"intra-class embedding spread: plain {plain:.3f} "
          f"-> center loss {center:.3f} ({plain / center:.1f}x tighter)")

    # -- 3. random hyperparameter search with early stopping -----------------
    xh = rng.normal(size=(512, 10)).astype(np.float32)
    wh = np.random.default_rng(5).normal(size=(10, 4)).astype(np.float32)
    yh = np.eye(4, dtype=np.float32)[np.argmax(xh @ wh, axis=1)]
    train, val = DataSet(xh[:384], yh[:384]), DataSet(xh[384:], yh[384:])

    from deeplearning4j_tpu.optimize.hpo import (Choice, LogUniform,
                                                 RandomSearch)

    def model_fn(p):
        conf = (NeuralNetConfiguration.builder().seed(1)
                .updater(Adam(p["lr"])).list()
                .layer(DenseLayer(n_in=10, n_out=p["width"],
                                  activation="relu"))
                .layer(OutputLayer(n_out=4, activation="softmax",
                                   loss="mcxent"))
                .set_input_type(InputType.feed_forward(10))
                .build())
        es = EarlyStoppingConfiguration(
            score_calculator=DataSetLossCalculator(
                ListDataSetIterator(val, 128)),
            epoch_termination_conditions=[
                MaxEpochsTerminationCondition(30),
                ScoreImprovementEpochTerminationCondition(5)])
        return EarlyStoppingTrainer(
            es, MultiLayerNetwork(conf).init(),
            ListDataSetIterator(train, 64, shuffle=True)).fit()

    search = RandomSearch(
        {"width": Choice(8, 32, 128), "lr": LogUniform(3e-4, 3e-2)},
        model_fn, lambda result, p: result.best_model_score,
        keep_models=True)
    best = search.optimize(n_trials=5, seed=7)
    for t in search.trials:
        print(f"  width={t.params['width']:<4} lr={t.params['lr']:.2e} "
              f"val loss {t.score:.4f}")
    ev = best.model.best_model.evaluate(ListDataSetIterator(val, 128))
    print(f"best config: {best.params} -> "
          f"val accuracy {ev.accuracy():.3f}")


if __name__ == "__main__":
    main()
