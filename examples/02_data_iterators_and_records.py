"""Built-in data iterators + record readers.

Mirrors tutorials "02. Built-in Data Iterators" and the DataVec bridge: MNIST
fetcher (cache-or-synthetic), CSV record reader → DataSet iterator, async
prefetch, and the native C++ prefetching loader.

Run: python examples/02_data_iterators_and_records.py
"""

import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.fetchers import IrisDataSetIterator, MnistDataSetIterator
from deeplearning4j_tpu.datasets.iterators import AsyncDataSetIterator
from deeplearning4j_tpu.datasets.records import CSVRecordReader, RecordReaderDataSetIterator
from deeplearning4j_tpu.native import NativeDataSetIterator, native_available


def main():
    # built-in fetchers
    mnist = MnistDataSetIterator(batch_size=128, train=True)
    batch = next(iter(mnist))
    print("MNIST batch:", batch.features.shape, batch.labels.shape,
          "(synthetic stand-in)" if mnist.synthetic else "(real cache)")
    iris = IrisDataSetIterator(batch_size=50)
    print("Iris batch:", next(iter(iris)).features.shape)

    # CSV records → one-hot classification DataSets
    with tempfile.NamedTemporaryFile("w", suffix=".csv", delete=False) as f:
        rng = np.random.default_rng(0)
        for i in range(100):
            feats = rng.normal(size=4)
            f.write(",".join(f"{v:.3f}" for v in feats) + f",{i % 3}\n")
        path = f.name
    reader_it = RecordReaderDataSetIterator(CSVRecordReader(path), batch_size=32,
                                            label_index=4, num_possible_labels=3)
    print("CSV batches:", [b.features.shape for b in reader_it])

    # async prefetch wrapper (background thread)
    async_it = AsyncDataSetIterator(reader_it, queue_size=2)
    print("async batches:", sum(1 for _ in async_it))

    # native C++ threaded loader
    x = rng.normal(size=(256, 6)).astype(np.float32)
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 256)]
    nat = NativeDataSetIterator(x, y, batch_size=64, shuffle=True, n_threads=2)
    print(f"native loader (C++ path live: {native_available()}):",
          [b.features.shape[0] for b in nat])


if __name__ == "__main__":
    main()
