"""Health & alerting: the observability loop closed end to end.

The third pillar (``observe/log.py``, ``observe/health.py``,
``observe/alerts.py``) on top of the spans + metrics from example 25 —
signals become *action*:

- structured JSON-lines logging with automatic ``trace_id``/``span_id``
  correlation (the Dapper contract: a log line emitted inside a traced
  run is findable from the trace id, including every stdlib ``logging``
  call through the bridge);
- a deliberately-diverging training run (SGD at lr=1000 on MSE explodes
  within a few steps): a ``TrainingWatchdog`` with the ``raise`` policy
  aborts the fit with ``WatchdogAlarm`` the step the loss goes
  non-finite, and the ``PreemptionHandler`` rollback flow restores the
  pre-divergence checkpoint;
- a saturated model server (``max_inflight=1``, slow model, concurrent
  burst): 429 rejections drive the error ratio of
  ``serving_requests_total`` over a multiwindow burn-rate SLO rule
  (Google SRE Workbook shape) — the alert FIRES, notifies its sink
  exactly once, and RESOLVES after recovery traffic, all on an injected
  ``ManualTimeSource`` clock (no waiting for real windows);
- the server's ``/livez?verbose=1`` health report and ``/alerts`` rule
  states over HTTP, and the shipped ``alert_rules.json`` validated with
  ``tools/validate_alert_rules.py``.

Run: python examples/26_health_and_alerting.py   (CPU-friendly, <1 min)
"""

import json
import os
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor
from urllib.request import urlopen

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Sgd
from deeplearning4j_tpu.observe import (AlertManager, CallbackSink, LogSink,
                                        TrainingWatchdog, WatchdogAlarm,
                                        attach_observability,
                                        default_registry, disable_tracing,
                                        disable_structured_logging,
                                        enable_structured_logging,
                                        enable_tracing, get_active_hub,
                                        get_logger, load_rules)
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
from deeplearning4j_tpu.util.preemption import PreemptionHandler

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
RULES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "alert_rules.json")


def diverging_training(tmp):
    print("=== 1. watchdog catches a diverging run; rollback recovers ===")
    rng = np.random.default_rng(0)
    x = rng.normal(size=(64, 8)).astype(np.float32)
    y = (x @ rng.normal(size=(8, 1)).astype(np.float32))
    conf = (NeuralNetConfiguration.builder().seed(1)
            .updater(Sgd(1000.0))  # deliberately explosive
            .list()
            .layer(DenseLayer(n_in=8, n_out=16, activation="tanh"))
            .layer(OutputLayer(n_in=16, n_out=1, activation="identity",
                               loss="mse"))
            .build())
    net = MultiLayerNetwork(conf).init()

    ckpt = os.path.join(tmp, "pre_divergence.zip")
    handler = PreemptionHandler(net, ckpt)
    handler.save()  # the known-good snapshot the rollback restores

    tracer = enable_tracing(metrics=default_registry())
    # ONE attachment path for TraceListener + watchdog; raise policy stops
    # the run the step the loss goes non-finite
    attach_observability(net, tracer=tracer, metrics=default_registry(),
                         model_name="diverging",
                         watchdog={"action": "raise",
                                   "divergence_windows": 3})
    it = ListDataSetIterator(DataSet(x, y), 16)
    slog = get_logger("example26")
    alarm = None
    with tracer.span("diverging_run") as sp:
        slog.info("starting deliberately-diverging fit")
        try:
            net.fit(it, epochs=50)
        except WatchdogAlarm as e:
            alarm = e
    assert alarm is not None, "watchdog never fired on an lr=1000 run"
    print(f"watchdog fired: {alarm}")

    # every structured record emitted inside the span carries its ids
    hub = get_active_hub()
    correlated = [r for r in hub.ring.records()
                  if r.trace_id == sp.trace_id]
    assert correlated, "no log records correlated to the run's trace"
    print(f"{len(correlated)} log record(s) carry trace_id "
          f"{sp.trace_id[:8]}… (incl. the watchdog finding)")

    restored, state = handler.rollback()
    for group in restored.params:
        for name, arr in group.items():
            assert np.all(np.isfinite(np.asarray(arr))), name
    print(f"rollback restored finite params from {os.path.basename(ckpt)} "
          f"(iteration {state['iteration']})\n")
    disable_tracing()


class SlowModel:
    """50 ms per batch: enough overlap for a burst to overflow admission."""

    def output(self, x):
        time.sleep(0.05)
        return np.asarray(x).sum(axis=tuple(range(1, np.asarray(x).ndim)),
                                 keepdims=True)


def saturated_serving():
    print("=== 2. saturated server drives a burn-rate alert ===")
    metrics = default_registry()
    rules = load_rules(RULES)
    clock = ManualTimeSource(0)
    notifications = []
    mgr = AlertManager(metrics, rules,
                       [LogSink(), CallbackSink(notifications.append)],
                       time_source=clock)

    registry = ModelRegistry(metrics=metrics, wait_ms=1.0)
    registry.register("slow", model=SlowModel())
    server = ModelServer(registry, metrics=metrics, max_inflight=1,
                         alerts=mgr)
    port = server.start()
    url = f"http://127.0.0.1:{port}"

    mgr.evaluate_once()  # baseline sample at t=0

    def predict():
        import urllib.error
        body = json.dumps({"inputs": [[1.0, 2.0]]}).encode()
        try:
            from urllib.request import Request
            with urlopen(Request(f"{url}/v1/models/slow/predict", body),
                         timeout=10) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    # burst: 16 concurrent requests against max_inflight=1 → mostly 429s
    with ThreadPoolExecutor(16) as pool:
        codes = list(pool.map(lambda _: predict(), range(16)))
    n_429 = codes.count(429)
    print(f"burst statuses: {sorted(set(codes))} ({n_429}/16 shed as 429)")
    assert n_429 > 0, "burst never overflowed admission"

    clock.advance(seconds=60)
    fired = mgr.evaluate_once()
    assert any(n.rule == "predict_slo_burn" and n.state == "firing"
               for n in fired), mgr.describe()
    print(f"fired: {[n.rule for n in fired if n.state == 'firing']}")

    # /alerts and /livez over HTTP while firing
    alerts = json.load(urlopen(f"{url}/alerts", timeout=5))
    assert "predict_slo_burn" in alerts["firing"]
    livez = json.load(urlopen(f"{url}/livez?verbose=1", timeout=5))
    print(f"/livez status={livez['status']} "
          f"({len(livez['checks'])} checks); "
          f"/alerts firing={alerts['firing']}")

    # recovery: sequential successes only, clock past the short window →
    # the short-window burn rate drops to 0 and the alert resolves
    for _ in range(4):
        assert predict() == 200
    clock.advance(seconds=400)
    resolved = mgr.evaluate_once()
    assert any(n.rule == "predict_slo_burn" and n.state == "resolved"
               for n in resolved), mgr.describe()
    burn_notes = [n for n in notifications if n.rule == "predict_slo_burn"]
    assert [n.state for n in burn_notes] == ["firing", "resolved"], \
        [n.state for n in burn_notes]
    print("resolved after recovery traffic; sink saw exactly one "
          "firing + one resolved notification\n")
    server.stop(drain=True, shutdown_registry=True)


def validate_shipped_rules():
    print("=== 3. shipped rules file passes the validator ===")
    sys.path.insert(0, TOOLS)
    from validate_alert_rules import validate_file
    errors = validate_file(RULES)
    assert not errors, errors
    print(f"OK {os.path.basename(RULES)}: "
          f"{len(load_rules(RULES))} rule(s) valid\n")


def main():
    with tempfile.TemporaryDirectory() as tmp:
        enable_structured_logging(
            path=os.path.join(tmp, "run.jsonl"), level="debug")
        try:
            diverging_training(tmp)
            saturated_serving()
            validate_shipped_rules()
            # the JSON-lines stream parses back, line by line
            with open(os.path.join(tmp, "run.jsonl")) as fh:
                lines = [json.loads(l) for l in fh]
            assert any("trace_id" in l for l in lines)
            print(f"structured log stream: {len(lines)} JSON lines, "
                  "trace-correlated")
        finally:
            disable_structured_logging()
    print("example 26 complete")


if __name__ == "__main__":
    main()
