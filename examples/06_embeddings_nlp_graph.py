"""Embeddings: Word2Vec, ParagraphVectors, DeepWalk/node2vec, t-SNE.

Mirrors the NLP and graph tutorials: train word vectors, infer a document
vector, embed a graph's vertices, project with t-SNE.

Run: python examples/06_embeddings_nlp_graph.py
"""

import numpy as np

from deeplearning4j_tpu.graph import DeepWalk, Graph, Node2Vec
from deeplearning4j_tpu.nlp.paragraph_vectors import ParagraphVectors
from deeplearning4j_tpu.nlp.word2vec import Word2Vec


CORPUS = ["the quick brown fox jumps over the lazy dog",
          "the dog sleeps while the quick fox runs",
          "foxes and dogs are animals",
          "cats chase the lazy dog sometimes",
          "the brown fox likes the brown dog"] * 8


def word2vec():
    w2v = Word2Vec(layer_size=24, window_size=3, min_word_frequency=2,
                   epochs=5, seed=1)
    w2v.fit(CORPUS)
    print("w2v nearest('fox'):", w2v.words_nearest("fox", 3))


def paragraph_vectors():
    pv = ParagraphVectors(layer_size=16, window_size=3, epochs=5, seed=2,
                          min_word_frequency=1)
    pv.fit(CORPUS)
    vec = pv.infer_vector("the quick fox")
    print("inferred doc vector:", vec.shape, "norm %.3f" % np.linalg.norm(vec))


def graph_embeddings():
    g = Graph(10)
    for c in (0, 5):
        for i in range(c, c + 5):
            for j in range(i + 1, c + 5):
                g.add_edge(i, j)
    g.add_edge(4, 5)  # bridge between the two cliques
    dw = DeepWalk(vector_size=16, window_size=2, learning_rate=0.05, seed=3)
    dw.fit(g, walk_length=10, epochs=30)
    print("DeepWalk: sim(0, 1)=%.3f (same clique)  sim(0, 9)=%.3f (other)"
          % (dw.similarity(0, 1), dw.similarity(0, 9)))

    nv = Node2Vec(vector_size=16, p=0.25, q=4.0, walks_per_vertex=8, seed=4)
    nv.fit(g, walk_length=10, epochs=15)
    print("node2vec nearest to 0:", list(nv.vertices_nearest(0, 3)))

    # t-SNE projection of the learned vectors
    from deeplearning4j_tpu.plot.tsne import Tsne
    proj = Tsne(n_components=2, perplexity=3.0, n_iter=120, seed=5).fit_transform(
        np.stack([dw.get_vertex_vector(i) for i in range(10)]))
    print("t-SNE projection shape:", proj.shape)


if __name__ == "__main__":
    word2vec()
    paragraph_vectors()
    graph_embeddings()
