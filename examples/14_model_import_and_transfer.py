"""Model import: Keras HDF5, DL4J config dialect, checkpoint round trips.

The reference's migration tier (SURVEY.md §2 modelimport): a model trained
in another framework keeps working here.

1. Keras → build a small CNN with the installed Keras, save legacy HDF5,
   import (`KerasModelImport.importKerasModelAndWeights:50` parity) and
   verify output equivalence on the same input;
2. Transfer learning on the imported net — freeze the conv trunk, replace
   the head, fine-tune (`TransferLearning.Builder`);
3. DL4J config dialect → a `MultiLayerConfiguration` JSON in the
   REFERENCE's serialization format imports into a native config;
4. ModelSerializer zip round trip (config + params + updater state).

Run: python examples/14_model_import_and_transfer.py   (needs keras; CPU ok)
"""

import json

import numpy as np


def main():
    import keras

    from deeplearning4j_tpu.modelimport.keras import KerasModelImport

    rng = np.random.default_rng(0)

    # -- 1. Keras CNN → HDF5 → import → equivalence --------------------------
    km = keras.Sequential([
        keras.layers.Input((12, 12, 1)),
        keras.layers.Conv2D(8, 3, activation="relu"),
        keras.layers.MaxPooling2D(2),
        keras.layers.Flatten(),
        keras.layers.Dense(16, activation="relu"),
        keras.layers.Dense(4, activation="softmax"),
    ])
    km.compile(loss="categorical_crossentropy", optimizer="sgd")
    km.save("/tmp/keras_cnn.h5")
    net = KerasModelImport.import_keras_sequential_model_and_weights(
        "/tmp/keras_cnn.h5")
    x = rng.normal(size=(4, 12, 12, 1)).astype(np.float32)
    theirs = np.asarray(km.predict(x, verbose=0))
    ours = np.asarray(net.output(x))
    print(f"Keras import equivalence: max|Δ| = {np.abs(ours - theirs).max():.2e}")

    # -- 2. transfer learning on the imported net ----------------------------
    from deeplearning4j_tpu.nn.layers import OutputLayer
    from deeplearning4j_tpu.nn.transferlearning import (
        FineTuneConfiguration,
        TransferLearning,
    )
    from deeplearning4j_tpu.nn.updaters import Adam

    tuned = (TransferLearning.Builder(net)
             .fine_tune_configuration(FineTuneConfiguration(updater=Adam(1e-2)))
             .set_feature_extractor(2)          # freeze conv trunk
             .remove_output_layer()
             .add_layer(OutputLayer(n_out=2, activation="softmax",
                                    loss="mcxent"))
             .build())
    y = np.eye(2, dtype=np.float32)[rng.integers(0, 2, 64)]
    x2 = rng.normal(size=(64, 12, 12, 1)).astype(np.float32)
    x2[y[:, 1] == 1] += 1.5
    for _ in range(60):
        tuned.fit(x2, y)
    acc = (np.asarray(tuned.output(x2)).argmax(-1) == y.argmax(-1)).mean()
    print(f"fine-tuned head accuracy (frozen trunk): {acc:.3f}")

    # -- 3. the reference's own JSON dialect imports -------------------------
    from deeplearning4j_tpu.modelimport.dl4j import import_dl4j_configuration

    dl4j_json = json.dumps({
        "backprop": True, "backpropType": "Standard",
        "confs": [
            {"layer": {"dense": {"activationFn": "relu", "nin": 8, "nout": 16,
                                 "layerName": "layer0"}}},
            {"layer": {"output": {"activationFn": "softmax", "nin": 16,
                                  "nout": 3, "layerName": "layer1",
                                  "lossFn": "MCXENT"}}},
        ]})
    conf = import_dl4j_configuration(dl4j_json)
    from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
    legacy = MultiLayerNetwork(conf).init()
    print(f"DL4J dialect import: {len(conf.layers)} layers, "
          f"output shape {np.asarray(legacy.output(np.zeros((2, 8), np.float32))).shape}")

    # -- 4. checkpoint zip round trip ----------------------------------------
    from deeplearning4j_tpu.util.model_serializer import (
        restore_multi_layer_network,
        write_model,
    )

    write_model(tuned, "/tmp/tuned.zip")
    back = restore_multi_layer_network("/tmp/tuned.zip")
    same = np.allclose(np.asarray(back.output(x2[:4])),
                       np.asarray(tuned.output(x2[:4])), atol=1e-6)
    print(f"ModelSerializer round trip exact: {same}")


if __name__ == "__main__":
    main()
