"""Nearest neighbors, clustering, t-SNE, and the k-NN REST server.

The reference's `deeplearning4j-nearestneighbors-parent` +
`deeplearning4j-core/plot` tier (SURVEY.md §2): VPTree exact search, the
MXU brute-force index (the TPU-native fast path — one batched distance
matmul instead of a pointer-chasing tree), KMeans on device, Barnes-Hut
t-SNE, and the REST server/client pair
(`NearestNeighborsServer.java:42` → `clustering/server.py`).

Run: python examples/13_clustering_knn_tsne.py   (CPU-friendly)
"""

import numpy as np

from deeplearning4j_tpu.clustering.bruteforce import BruteForceNearestNeighbors
from deeplearning4j_tpu.clustering.kmeans import KMeansClustering
from deeplearning4j_tpu.clustering.server import (
    NearestNeighborsClient,
    NearestNeighborsServer,
)
from deeplearning4j_tpu.clustering.vptree import VPTree
from deeplearning4j_tpu.plot.tsne import BarnesHutTsne


def blobs(rng, n_per=80, centers=((0, 0), (8, 8), (0, 8)), dim=16):
    """Three well-separated gaussian blobs embedded in `dim` dimensions."""
    out, labels = [], []
    for ci, c in enumerate(centers):
        mu = np.zeros(dim)
        mu[:2] = c
        out.append(rng.normal(size=(n_per, dim)) * 0.5 + mu)
        labels.extend([ci] * n_per)
    return np.concatenate(out).astype(np.float32), np.array(labels)


def main():
    rng = np.random.default_rng(0)
    x, labels = blobs(rng)

    # -- exact VPTree vs MXU brute-force: same neighbors --------------------
    tree = VPTree(x, distance="euclidean")
    bf = BruteForceNearestNeighbors(x, distance="euclidean")
    q = x[5]
    d_tree, i_tree = tree.search(q, k=5)
    d_bf, i_bf = bf.search(q[None], k=5)
    print(f"VPTree == brute-force neighbors: {set(i_tree) == set(i_bf[0])}")

    # -- KMeans on device ----------------------------------------------------
    km = KMeansClustering.setup(cluster_count=3, max_iteration_count=50, seed=1)
    km.fit(x)                      # returns the (k, D) centers
    assignments = km.assignments   # per-point cluster ids from the last sweep
    # cluster purity: each found cluster should map to one true blob
    purity = np.mean([
        np.bincount(labels[assignments == c]).max()
        / max(1, (assignments == c).sum())
        for c in range(3)])
    print(f"KMeans purity over 3 blobs: {purity:.3f}")

    # -- Barnes-Hut t-SNE: blobs stay separated in 2-D -----------------------
    emb = BarnesHutTsne(n_components=2, n_iter=120, perplexity=20,
                        seed=7).fit_transform(x)
    centroids = np.stack([emb[labels == c].mean(0) for c in range(3)])
    spread = np.linalg.norm(
        centroids[:, None] - centroids[None, :], axis=-1)[np.triu_indices(3, 1)]
    within = np.mean([np.linalg.norm(emb[labels == c]
                                     - centroids[c], axis=1).mean()
                      for c in range(3)])
    print(f"t-SNE blob separation: centroid spread {spread.min():.1f} "
          f"vs within-blob radius {within:.1f}")

    # -- REST serving (NearestNeighborsServer parity) ------------------------
    server = NearestNeighborsServer(points=x, similarity_function="euclidean",
                                    port=0, labels=[str(l) for l in labels])
    port = server.start()
    client = NearestNeighborsClient(f"http://127.0.0.1:{port}")
    got = client.knn(index=5, k=5)          # excludes the query point itself
    got_new = client.knn_new(x[5], k=5)
    d6, i6 = bf.search(q[None], k=6)
    local = {int(i) for i in i6[0] if i != 5}
    same = {r["index"] for r in got["results"]} == local
    print(f"REST k-NN agrees with local search: {same}; "
          f"knn_new returned {len(got_new['results'])} hits")
    server.stop()


if __name__ == "__main__":
    main()
