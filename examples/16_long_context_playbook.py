"""Long-context playbook: flash attention, sequence parallelism, remat, TBPTT.

Long sequences are first-class here (the reference's longest-sequence tool
is truncated BPTT; SURVEY.md §5). This example walks the four levers and
what each one buys, on a small causal LM so it runs anywhere:

1. **Causal flash attention** at the helper seam — O(T) memory, skips the
   masked upper triangle. Measured on v5e: 1.45x LM training at T=2048,
   2.64x at T=4096 (BASELINE.md). Registered once, serves every causal
   attention layer whose shapes it supports; outputs unchanged.
2. **Sequence parallelism** — `SequenceParallelAttentionHelper(causal=True)`
   shards the SEQUENCE axis over a mesh (ring or Ulysses all-to-all), so a
   context that cannot fit one chip's HBM spreads across the slice. Same
   outputs, one registration line.
3. **Gradient checkpointing** — rematerialize per-layer activations in the
   backward pass: measured 5.2x less temp HBM on a 6-block attention stack
   at T=512 (BASELINE.md).
4. **Truncated BPTT over the graph** — Transformer-XL-style chunking: KV
   caches and positional offsets carry across chunks, so a sequence longer
   than the attention window still trains end to end.

Run: python examples/16_long_context_playbook.py   (CPU-friendly sizes)
"""

import numpy as np

import jax

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn import helpers
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.parallel.mesh import SEQUENCE_AXIS, make_mesh
from deeplearning4j_tpu.parallel.ring import SequenceParallelAttentionHelper
from deeplearning4j_tpu.zoo.models import TransformerLM, lm_labels

VOCAB = 50
T = 32


def small_lm(gradient_checkpointing=False):
    m = TransformerLM(vocab_size=VOCAB, max_length=T, n_layers=2,
                      d_model=32, n_heads=8, d_ff=64, seed=3)
    conf = m.conf()
    conf.global_conf.gradient_checkpointing = gradient_checkpointing
    net = ComputationGraph(conf)
    net.init()
    return net


def main():
    rng = np.random.default_rng(0)
    ids = rng.integers(0, VOCAB, (4, T)).astype(np.float32)

    # -- 1. causal flash attention (TPU-only kernel; gate like the seam) ----
    from deeplearning4j_tpu.nn.pallas_kernels import PallasFlashAttentionHelper
    net = small_lm()
    ref = np.asarray(net.output(ids))
    if jax.default_backend() == "tpu":
        helpers.set_helper("attention", PallasFlashAttentionHelper(causal=True))
        try:
            out = np.asarray(net.output(ids))
        finally:
            helpers.clear_helper("attention")
        # (shapes here are below the kernel's 128-step gate, so it falls
        # back — at T>=128 with dh in {64,128,256} the kernel engages)
        print(f"flash seam registered cleanly; outputs equal: "
              f"{np.allclose(out, ref, atol=1e-3)}")
    else:
        print("flash attention kernel needs the TPU backend — skipped")

    # -- 2. sequence parallelism over a device mesh -------------------------
    n_dev = len(jax.devices())
    shards = max(d for d in (1, 2, 4, 8) if n_dev % d == 0 and T % d == 0
                 and d <= n_dev)
    if shards > 1:
        mesh = make_mesh({SEQUENCE_AXIS: shards})
        for strategy in ("ring", "ulysses"):
            helpers.set_helper("attention", SequenceParallelAttentionHelper(
                mesh, strategy=strategy, causal=True))
            try:
                out = np.asarray(net.output(ids))
            finally:
                helpers.clear_helper("attention")
            print(f"{strategy:7s} sequence-parallel over {shards} devices: "
                  f"outputs unchanged = {np.allclose(out, ref, atol=1e-4)}")
    else:
        print("single device: sequence parallelism needs a mesh — skipped")

    # -- 3. gradient checkpointing ------------------------------------------
    y = lm_labels(ids, VOCAB)
    for remat in (False, True):
        net_r = small_lm(gradient_checkpointing=remat)
        net_r.fit(ids, y)
        print(f"gradient_checkpointing={remat}: loss {net_r.score_:.3f} "
              f"(same math, backward rematerializes activations)")

    # -- 4. TBPTT: train beyond the attention window ------------------------
    m = TransformerLM(vocab_size=VOCAB, max_length=T, n_layers=1,
                      d_model=16, n_heads=2, d_ff=32, seed=5)
    conf = m.conf()
    conf.backprop_type = "truncated_bptt"
    conf.tbptt_fwd_length = 8              # 4 chunks per sequence
    tb = ComputationGraph(conf).init()
    for _ in range(5):
        tb.fit(ids, y)
    print(f"TBPTT (chunk 8 over T={T}): {tb.iteration} chunk steps, "
          f"loss {tb.score_:.3f} — KV caches and positions carry across "
          f"chunks (Transformer-XL style)")


if __name__ == "__main__":
    main()
