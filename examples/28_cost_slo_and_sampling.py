"""Request cost & SLOs: the economics plane closed end to end.

The fourth observability pillar (``observe/cost.py``, ``observe/slo.py``,
tail sampling in ``observe/fleet.py``, ``capture_bundle`` in
``observe/incident.py``) on top of the spans/metrics/alerts from
examples 25-26 — *who pays, is the promise kept, and can you open the
trace that broke it*:

- a cost-metered model server under chaos traffic (one ``slow_forward``
  fault): every dispatcher-served response carries ``X-Device-Ms`` — its
  row-weighted share of the coalesced batches' device time — and the
  ledger's conservation invariant (attributed + unattributed == total)
  holds exactly;
- a declarative latency SLO (``observe/slo.py`` schema, the same file
  format ``serve --slo`` loads) whose threshold sits below the lowest
  histogram bucket, so every request is a deterministic violation: the
  auto-generated multiwindow burn-rate rule FIRES exactly once on an
  injected ``ManualTimeSource`` clock and RESOLVES once traffic stops —
  no wall-clock windows, no sleeps in the control path;
- the slow request's trace id shows up as the tail-bucket **exemplar**
  on ``serving_request_latency_seconds`` (OpenMetrics
  ``# {trace_id="…"}`` annotation), and ``/debug/capture?seconds=N``
  returns its complete trace — client span → http_request →
  inference_request/queue_wait — which validates clean under
  ``tools/validate_trace.py``;
- a :class:`TailSampler` installed as the tracer's recorder keeps the
  slow/error traces on disk and drops the boring ones, with every
  outcome counted;
- the shipped ``examples/slo_config.json`` passes
  ``tools/validate_slo_config.py``.

Run: python examples/28_cost_slo_and_sampling.py   (CPU-friendly, <1 min)
"""

import json
import os
import sys
import tempfile

import numpy as np

from deeplearning4j_tpu.observe import (AlertManager, CallbackSink, LogSink,
                                        MetricsRegistry, TailSampler, Tracer,
                                        SpanFileWriter, disable_tracing,
                                        enable_tracing, load_slos,
                                        parse_prometheus_text,
                                        read_span_file)
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
from deeplearning4j_tpu.serving import ModelRegistry, ModelServer
from deeplearning4j_tpu.serving.client import ModelServingClient
from deeplearning4j_tpu.util import faultinject
from urllib.request import Request, urlopen

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")
SLO_CONFIG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "slo_config.json")

# every request violates this (threshold below the lowest latency bucket)
# — the deterministic burn knob: no wall-clock sleeps needed to blow the
# error budget, the bucket math does it
SLOS = {"slos": [{
    "name": "econ-latency", "sli": "latency",
    "metric": "serving_request_latency_seconds",
    "labels": {"model": "econ"},
    "threshold_ms": 0.001, "objective": 0.99,
    "windows": [{"long_s": 3600, "short_s": 10, "factor": 2.0}],
    "severity": "page"}]}


class TinyModel:
    """Microseconds per batch — the slow_forward fault IS the latency."""

    def output(self, x):
        x = np.asarray(x)
        return x.sum(axis=tuple(range(1, x.ndim)), keepdims=True)


def main():
    tmp = tempfile.mkdtemp(prefix="example28_")
    metrics = MetricsRegistry()
    span_path = os.path.join(tmp, "kept_spans.jsonl")

    # tail sampling at the recorder/sink seam: the ring records EVERY
    # span (the capture window below needs that), the file only earns
    # complete traces that are slow (>=100 ms at their root) or errored
    sampler = TailSampler(SpanFileWriter(span_path, label="example28"),
                          slow_ms={"client_predict": 100.0},
                          default_slow_ms=100.0, metrics=metrics)
    enable_tracing(Tracer(sampler), metrics=metrics)

    slo_set = load_slos(SLOS)
    clock = ManualTimeSource(0)
    notes = []
    mgr = AlertManager(metrics, slo_set.rules(),
                       [LogSink(), CallbackSink(notes.append)],
                       time_source=clock)

    registry = ModelRegistry(metrics=metrics, wait_ms=1.0)
    registry.register("econ", model=TinyModel())
    server = ModelServer(registry, metrics=metrics, alerts=mgr, slo=slo_set)
    port = server.start()
    url = f"http://127.0.0.1:{port}"

    print("=== 1. chaos traffic through a cost-metered, tail-sampled "
          "server ===")
    # the 4th dispatched forward of 'econ' blocks 250 ms — a latency
    # spike the sampler must keep and the tail bucket must exemplify
    faultinject.set_plan(faultinject.FaultPlan.parse({"faults": [
        {"type": "slow_forward", "model": "econ", "step": 3,
         "duration_s": 0.25}]}))
    client = ModelServingClient(url)
    mgr.evaluate_once()  # baseline sample at t=0

    trace_ids = []
    for _ in range(6):
        out = client.predict("econ", [[1.0, 2.0, 3.0, 4.0]])
        assert np.asarray(out).shape == (1, 1)
        trace_ids.append(client.last_trace_id)
    slow_tid = trace_ids[3]
    assert slow_tid is not None and len(set(trace_ids)) == 6

    # X-Device-Ms: the per-request bill, echoed on the wire. Billing is
    # keyed by trace id through the dispatcher, so it rides any plain
    # HTTP request too (the header lands once the batch is ledgered)
    body = json.dumps({"inputs": [[1.0, 2.0, 3.0, 4.0]]}).encode()
    device_hdr = None
    for _ in range(5):
        with urlopen(Request(f"{url}/v1/models/econ/predict", body),
                     timeout=10) as r:
            device_hdr = r.headers.get("X-Device-Ms")
        if device_hdr is not None:
            break
    assert device_hdr is not None, "no X-Device-Ms header on any response"
    print(f"slow trace {slow_tid[:8]}…; X-Device-Ms={device_hdr}")

    slow_ms = server.cost.device_ms(slow_tid)
    assert slow_ms is not None and slow_ms >= 200.0, slow_ms
    cons = server.cost.conservation("econ")
    assert cons["ok"], cons
    print(f"ledger: slow request billed {slow_ms:.1f} device-ms; "
          f"conservation error {cons['error_ms']:.9f} ms over "
          f"{cons['batches']} batch(es)\n")

    print("=== 2. the SLO's burn-rate rule fires once and resolves ===")
    clock.advance(seconds=5)
    fired = mgr.evaluate_once()
    assert any(n.rule == "slo_burn:econ-latency" and n.state == "firing"
               for n in fired), mgr.describe()
    status = json.load(urlopen(f"{url}/slo", timeout=5))
    entry = status["slos"][0]
    assert entry["alert"]["state"] == "firing"
    assert entry["compliance"]["met"] is False
    assert entry["burn"][0]["active"] is True
    print(f"/slo: compliance ratio={entry['compliance']['ratio']:.3f} "
          f"(objective {entry['objective']}), "
          f"burn long={entry['burn'][0]['long']:.1f}x budget, "
          f"alert={entry['alert']['state']}")

    # recovery is traffic silence: the short window's delta drains to 0
    clock.advance(seconds=400)
    resolved = mgr.evaluate_once()
    assert any(n.rule == "slo_burn:econ-latency" and n.state == "resolved"
               for n in resolved), mgr.describe()
    burn_notes = [n for n in notes if n.rule == "slo_burn:econ-latency"]
    assert [n.state for n in burn_notes] == ["firing", "resolved"], \
        [n.state for n in burn_notes]
    print("resolved; sink saw exactly one firing + one resolved "
          "notification\n")

    print("=== 3. tail-bucket exemplar -> /debug/capture -> valid "
          "trace ===")
    parsed = parse_prometheus_text(metrics.exposition())
    tail_le, tail_exemplar = -1.0, None
    for (series, labels), ex in parsed.exemplars.items():
        ld = dict(labels)
        if series != "serving_request_latency_seconds_bucket" \
                or ld.get("model") != "econ":
            continue
        le = float(ld["le"])
        if le != float("inf") and le > tail_le:
            tail_le, tail_exemplar = le, ex
    assert tail_exemplar is not None, "no latency exemplars exposed"
    ex_tid = tail_exemplar.labels.get("trace_id")
    assert ex_tid == slow_tid, (ex_tid, slow_tid)
    print(f"le={tail_le} bucket exemplar names the slow trace "
          f"{ex_tid[:8]}… (value {tail_exemplar.value:.3f}s)")

    bundle = json.load(urlopen(f"{url}/debug/capture?seconds=60",
                               timeout=10))
    events = bundle["trace"]["traceEvents"]
    names = {e["name"] for e in events
             if e.get("args", {}).get("trace_id") == slow_tid}
    assert {"client_predict", "http_request", "inference_request",
            "queue_wait"} <= names, names
    assert any(e["name"] == "batch_execute" for e in events)
    assert bundle["cost"]["totals"]["conservation"]["ok"]
    assert bundle["sampler"] is not None  # the sampler self-identifies
    trace_path = os.path.join(tmp, "capture_trace.json")
    with open(trace_path, "w") as fh:
        json.dump(bundle["trace"], fh)
    sys.path.insert(0, TOOLS)
    from validate_trace import validate_file as validate_trace_file
    errors = validate_trace_file(trace_path)
    assert not errors, errors
    print(f"capture: {bundle['bounds']['span_count']} span(s), slow trace "
          f"complete ({sorted(names)}), chrome trace validates clean\n")

    print("=== 4. sampler accounting + shipped config lint ===")
    faultinject.set_plan(None)
    server.stop(drain=True, shutdown_registry=True)
    disable_tracing()
    sampler.close()

    acct = sampler.describe()
    assert acct["kept_traces"] >= 1, acct
    assert acct["dropped_traces"] >= 1, acct       # fast traces drop
    assert acct["keep_reasons"].get("slow", 0) >= 1, acct
    kept = read_span_file(span_path)
    kept_ids = {s["trace"] for s in kept["spans"]}
    assert slow_tid in kept_ids, "slow trace never reached the sink"
    fast_kept = kept_ids & set(trace_ids[:3])
    assert not fast_kept, f"fast traces leaked to disk: {fast_kept}"
    print(f"sampler: kept {acct['kept_traces']} trace(s) "
          f"({acct['keep_reasons']}), dropped {acct['dropped_traces']}; "
          f"{len(kept['spans'])} span(s) on disk, slow trace among them")

    from validate_slo_config import validate_file as validate_slo_file
    errors = validate_slo_file(SLO_CONFIG)
    assert not errors, errors
    print(f"OK {os.path.basename(SLO_CONFIG)}: "
          f"{len(load_slos(SLO_CONFIG).slos)} slo(s) valid")
    print("example 28 complete")


if __name__ == "__main__":
    main()
