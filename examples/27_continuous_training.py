"""Continuous training: stream -> fit -> eval gate -> canary -> promote,
as one crash-safe pipeline (``deeplearning4j_tpu/pipeline/``).

The loop every production-ML platform ends up hand-rolling (TFX's
continuous-training push, the "pipeline glue" of Sculley et al.), built
from pieces this framework already had and a journaled state machine
that makes it safe:

1. **healthy cycle**: a streaming route feeds mini-epoch incremental
   ``fit()`` on a candidate cloned from the serving version (watchdog +
   TraceListener attached); the candidate passes the held-out eval gate,
   canaries at 25% then 50% of live traffic (deterministic weighted
   round-robin, shadow diffs recorded off the response path, all on a
   ``ManualTimeSource`` — no real waiting) and auto-PROMOTEs into the
   live slot;
2. **regression cycle**: the stream turns garbage (inverted labels), the
   retrained candidate fails the gate and the run auto-ROLLBACKs —
   the bad model never receives a single live request;
3. **journal audit**: the fenced journal shows exactly one PROMOTE and
   one ROLLBACK commit, the canary ramp notes, and the gate numbers that
   justified each decision — and the shipped pipeline config validates
   through ``tools/validate_pipeline_config.py``.

Run: python examples/27_continuous_training.py   (CPU-friendly, <2 min)
"""

import json
import os
import sys
import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.observe.metrics import MetricsRegistry
from deeplearning4j_tpu.parallel.time_source import ManualTimeSource
from deeplearning4j_tpu.pipeline import (ContinuousPipeline, PipelineConfig,
                                         StreamBuffer)
from deeplearning4j_tpu.serving import ModelRegistry
from deeplearning4j_tpu.streaming import Route

HERE = os.path.dirname(os.path.abspath(__file__))
TOOLS = os.path.join(os.path.dirname(HERE), "tools")
CONFIG = os.path.join(HERE, "pipeline_config.json")

rng = np.random.default_rng(7)
W = rng.normal(size=(8, 2)).astype(np.float32)


def make_data(n, garbage=False):
    x = rng.normal(size=(n, 8)).astype(np.float32)
    labels = (x @ W).argmax(1)
    if garbage:  # the regression: every label inverted — training on
        labels = 1 - labels  # this actively pushes the candidate wrong
    return x, np.eye(2, dtype=np.float32)[labels]


def build_baseline():
    conf = (NeuralNetConfiguration.builder().seed(1).list()
            .layer(DenseLayer(n_out=16, activation="relu"))
            .layer(OutputLayer(n_out=2))
            .set_input_type(InputType.feed_forward(8)).build())
    net = MultiLayerNetwork(conf).init()
    net.fit(DataSet(*make_data(128)), epochs=3)
    return net


def run_cycle(registry, state_dir, config, clock, metrics, eval_set,
              garbage=False):
    buffer = StreamBuffer()
    batches = [DataSet(*make_data(16, garbage=garbage)) for _ in range(6)]
    route = Route().from_source(batches).to_callable(buffer.put).start()

    def canary_wait(poll_s):
        # between ticks: drive live traffic (so weighted routing + shadow
        # observe real forwards) and advance the injected clock
        for i in range(4):
            registry.predict("model", eval_set.features[2 * i:2 * i + 2])
        clock.advance(seconds=6)

    pipe = ContinuousPipeline(
        registry, "model", state_dir, config=config, buffer=buffer,
        route=route, eval_set=eval_set, metrics=metrics, time_source=clock,
        sample_input=eval_set.features[:1], canary_wait=canary_wait)
    summary = pipe.run_cycle()
    assert route.join(timeout=10) == len(batches)  # drained, not stuck
    return pipe, summary


def main():
    config = PipelineConfig.parse(CONFIG)
    metrics = MetricsRegistry()
    clock = ManualTimeSource(0)
    eval_set = DataSet(*make_data(64))

    registry = ModelRegistry(metrics=metrics, wait_ms=1.0)
    baseline = build_baseline()
    registry.register("model", model=baseline,
                      sample_input=eval_set.features[:1])
    print(f"baseline serving as v1 "
          f"(warmup: {registry.warmup_state('model')['status']})")

    with tempfile.TemporaryDirectory() as state_dir:
        print("\n=== 1. healthy cycle: stream -> gate -> canary -> "
              "PROMOTE ===")
        pipe, summary = run_cycle(registry, state_dir, config, clock,
                                  metrics, eval_set)
        print(f"run {summary['run']}: {summary['outcome']} "
              f"(live v{summary['live_version']})")
        assert summary["outcome"] == "PROMOTE", summary
        assert registry.get("model").current_version == 2

        # the canary's data plane left its audit trail in the metrics
        exposition = metrics.exposition()
        assert "serving_canary_fraction" in exposition
        assert "shadow_requests_total" in exposition
        ramps = [r for r in pipe.sm.stage_history(1)
                 if r.get("event") == "note"
                 and r.get("message") == "canary ramp"]
        print("canary ramp:", [r["data"]["fraction"] for r in ramps],
              "| shadow:",
              [r for r in pipe.sm.stage_history(1)
               if r.get("stage") == "CANARY"
               and r.get("event") == "commit"][0]["data"]["shadow"])

        print("\n=== 2. regression cycle: garbage stream -> gate FAIL -> "
              "ROLLBACK ===")
        pipe2, summary2 = run_cycle(registry, state_dir, config, clock,
                                    metrics, eval_set, garbage=True)
        print(f"run {summary2['run']}: {summary2['outcome']} "
              f"(live v{summary2['live_version']})")
        assert summary2["outcome"] == "ROLLBACK", summary2
        assert registry.get("model").current_version == 2  # unchanged
        gate = [r for r in pipe2.sm.stage_history(2)
                if r.get("stage") == "EVAL"
                and r.get("event") == "commit"][0]["data"]
        print(f"gate: candidate loss {gate['candidate']:.4f} vs "
              f"threshold {gate['threshold']:.4f} -> FAIL")

        print("\n=== 3. journal audit: one PROMOTE, one ROLLBACK, "
              "never both per run ===")
        records = pipe2.sm.journal.records()
        terminals = [r for r in records if r.get("event") == "commit"
                     and r.get("stage") in ("PROMOTE", "ROLLBACK")]
        assert [(r["run"], r["stage"]) for r in terminals] == \
            [(1, "PROMOTE"), (2, "ROLLBACK")], terminals
        print(f"{len(records)} journal records; terminals: "
              f"{[(r['run'], r['stage']) for r in terminals]}")

    sys.path.insert(0, TOOLS)
    from validate_pipeline_config import validate_file
    errors = validate_file(CONFIG)
    assert not errors, errors
    print(f"\nOK {os.path.basename(CONFIG)}: validates clean")

    for line in metrics.exposition().splitlines():
        if line.startswith(("pipeline_runs_total", "shadow_")):
            print(line)
    print("example 27 complete")


if __name__ == "__main__":
    main()
