"""Serving and observability: ParallelInference, StatsListener, UI server.

The reference's operational tier in one script (SURVEY.md §2/§5):

- train with a `StatsListener` routing per-iteration stats (score, param/
  gradient magnitudes, histograms, memory) into a `StatsStorage`
  (`BaseStatsListener` → `InMemoryStatsStorage`, the Play UI's data feed);
- serve the trained model through `ParallelInference` in BATCHED mode —
  concurrent callers' requests coalesce into device-sized batches
  (`ParallelInference.java:32`, `InferenceMode.BATCHED`);
- hot-swap the served model atomically with `update_model`;
- start the dashboard (`UIServer` ≙ `PlayUIServer.java:53`) and read the
  same JSON the browser modules consume;
- export a phase timeline from `TrainingStats` (`StatsUtils` timeline) —
  with `NTPTimeSource` the stamps are comparable across hosts.

Run: python examples/12_serving_and_observability.py   (CPU-friendly)
"""

import json
import threading
import urllib.request

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.parallel.inference import ParallelInference
from deeplearning4j_tpu.ui.server import UIServer
from deeplearning4j_tpu.ui.stats import StatsListener
from deeplearning4j_tpu.ui.storage import InMemoryStatsStorage


def build_net(seed=7):
    conf = (NeuralNetConfiguration.builder().seed(seed).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=20, n_out=32, activation="relu"))
            .layer(OutputLayer(n_out=3, activation="softmax", loss="mcxent"))
            .set_input_type(InputType.feed_forward(20))
            .build())
    return MultiLayerNetwork(conf).init()


def main():
    rng = np.random.default_rng(0)
    n = 512
    x = rng.normal(size=(n, 20)).astype(np.float32)
    w = rng.normal(size=(20, 3)).astype(np.float32)
    cls = np.argmax(x @ w, axis=1)
    y = np.eye(3, dtype=np.float32)[cls]

    # -- train with the stats pipeline attached -----------------------------
    storage = InMemoryStatsStorage()
    net = build_net()
    net.set_listeners(StatsListener(storage, session_id="serving-demo"))
    net.fit(ListDataSetIterator(DataSet(x, y), 64, shuffle=True), epochs=10)
    print(f"trained; stats sessions recorded: {storage.list_session_ids()}")

    # -- batched parallel inference -----------------------------------------
    pi = ParallelInference(net, mode="batched", max_batch_size=64)
    results = {}

    def client(i):
        results[i] = pi.output(x[i * 8:(i + 1) * 8])

    threads = [threading.Thread(target=client, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    served = np.concatenate([results[i] for i in range(8)])
    direct = np.asarray(net.output(x[:64]))
    print(f"batched serving == direct output: "
          f"{np.allclose(served, direct, atol=1e-5)}")

    # hot-swap: retrained model replaces the served one atomically
    net2 = build_net(seed=8)
    net2.fit(ListDataSetIterator(DataSet(x, y), 64), epochs=10)
    pi.update_model(net2)
    acc = (np.asarray(pi.output(x)).argmax(-1) == cls).mean()
    print(f"accuracy after hot-swap: {acc:.3f}")
    pi.shutdown()

    # -- dashboard: the JSON the browser modules read -----------------------
    ui = UIServer(port=0)          # pick a free port
    ui.attach(storage)
    port = ui.start()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/train/sessions") as r:
        sessions = json.loads(r.read())
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/train/overview/serving-demo") as r:
        overview = json.loads(r.read())
    print(f"UI sessions: {sessions}; overview keys: {sorted(overview)[:5]}")
    ui.stop()


if __name__ == "__main__":
    main()
