"""Unified tracing + metrics: one timeline across training and serving.

The observe/ layer end to end — the Dapper-style answer to "where did this
millisecond go" that the reference's listener/StatsListener/training-UI
stack never had:

- enable process-wide tracing (``observe.enable_tracing``) with the JAX
  compile hook: every XLA compile becomes an ``xla_compile`` span nested
  under whatever triggered it, so step-0 compilation and later recompiles
  show up loudly;
- train data-parallel over the mesh with ``ParallelWrapper`` — per-step
  ``train_step`` spans (device-synced, with loss/batch attrs) — plus a
  ``TraceListener`` that exports ``training_*`` Prometheus series through
  the SAME registry the serving tier scrapes;
- serve the trained model and call it with ``ModelServingClient`` while a
  client span is open: the W3C ``traceparent`` header joins client →
  ``http_request`` → ``queue_wait``/``batch_execute`` (dispatcher thread)
  into ONE trace, and the server echoes ``X-Trace-Id``;
- run a traced streaming route (per-transform spans);
- export everything as a Chrome trace-event JSON (loadable in
  ``chrome://tracing`` / Perfetto), validate it with
  ``tools/validate_trace.py``, and print the terminal timeline;
- scrape ``/metrics`` and show the ``training_*`` and serving series side
  by side — one exposition for the whole stack.

Run: python examples/25_tracing_and_profiling.py   (CPU-friendly, ~1 min)
"""

import json
import os
import sys
import tempfile

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet, ListDataSetIterator
from deeplearning4j_tpu.nn.conf import NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import DenseLayer, OutputLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam
from deeplearning4j_tpu.observe import (TraceListener, default_registry,
                                        disable_tracing, enable_tracing,
                                        parse_prometheus_text)
from deeplearning4j_tpu.parallel import ParallelWrapper
from deeplearning4j_tpu.serving import (ModelRegistry, ModelServer,
                                        ModelServingClient)
from deeplearning4j_tpu.streaming.route import Route

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


def main():
    rng = np.random.default_rng(7)
    x = rng.normal(size=(256, 12)).astype(np.float32)
    w = rng.normal(size=(12, 3)).astype(np.float32)
    y = np.eye(3, dtype=np.float32)[np.argmax(x @ w, axis=1)]

    metrics = default_registry()
    tracer = enable_tracing(metrics=metrics)  # + JAX compile hook

    # -- traced training: ParallelWrapper steps + TraceListener bridge -----
    conf = (NeuralNetConfiguration.builder().seed(7).updater(Adam(1e-2))
            .list()
            .layer(DenseLayer(n_in=12, n_out=24, activation="relu"))
            .layer(OutputLayer(n_in=24, n_out=3, activation="softmax",
                               loss="negativeloglikelihood"))
            .build())
    net = MultiLayerNetwork(conf).init()
    net.add_listeners(TraceListener(tracer, metrics, model_name="demo"))
    pw = ParallelWrapper(net, metrics=metrics, metrics_name="demo")
    pw.fit(ListDataSetIterator(DataSet(x, y), 64), epochs=2)

    compile_spans = [s for s in tracer.recorder.spans()
                     if s.name == "xla_compile"]
    step_spans = [s for s in tracer.recorder.spans()
                  if s.name == "train_step"]
    print(f"training: {len(step_spans)} train_step spans, "
          f"{len(compile_spans)} xla_compile spans "
          f"(step 0 pays the compile; steady state recompiles would be loud)")

    # -- traced serving: traceparent joins client, HTTP and dispatcher -----
    registry = ModelRegistry(metrics=metrics, wait_ms=1.0)
    registry.register("demo", model=net)
    server = ModelServer(registry, metrics=metrics)
    server.start()
    try:
        client = ModelServingClient(server.url)
        with tracer.span("user_code"):  # the client span parents under this
            out = client.predict("demo", x[:8])
        print(f"served 1 request: outputs {np.asarray(out).shape}, "
              f"server echoed X-Trace-Id={client.last_trace_id}")

        # -- a traced streaming route (per-transform spans) ----------------
        sunk = []
        (Route().from_source([x[i:i + 4] for i in range(0, 16, 4)])
         .transform(lambda b: b * 2.0)
         .filter(lambda b: b.shape[0] == 4)
         .to_list(sunk)).run()
        print(f"routed {len(sunk)} mini-batches through a traced pipeline")

        # -- one /metrics exposition for train AND serve -------------------
        series = parse_prometheus_text(client.metrics_text())
        training = sorted(k for k in series if k.startswith("training_"))
        serving = sorted(k for k in series if k.startswith("serving_")
                         or k.startswith("inference_"))
        print("training series:", ", ".join(training))
        print("serving  series:", ", ".join(serving))
        assert "training_steps_total" in series
        assert "training_step_seconds_bucket" in series
    finally:
        server.stop(drain=True, shutdown_registry=True)
        disable_tracing()

    # -- export: Chrome trace JSON + schema validation + text timeline -----
    trace_path = os.path.join(tempfile.mkdtemp(), "train_and_serve.json")
    tracer.write_chrome_trace(trace_path)
    sys.path.insert(0, TOOLS)
    from validate_trace import validate_file
    errors = validate_file(trace_path)
    assert not errors, errors
    n_events = len(json.load(open(trace_path))["traceEvents"])
    print(f"wrote {trace_path}: {n_events} Chrome trace events, "
          f"schema-valid (load it in chrome://tracing or ui.perfetto.dev)")

    names = {s.name for s in tracer.recorder.spans()}
    for expected in ("parallel_fit", "train_step", "train_iteration",
                     "xla_compile", "client_predict", "http_request",
                     "inference_request", "queue_wait", "batch_execute",
                     "route.run"):
        assert expected in names, (expected, sorted(names))
    print("\nlast spans (terminal timeline):")
    print(tracer.timeline(limit=25))


if __name__ == "__main__":
    main()
