"""Example 20 — the text-classification pipeline, end to end.

Covers the reference's NLP data tier the way a DL4J user would use it:
word2vec embeddings -> CnnSentenceDataSetIterator (Kim-2014 CNN batches)
-> Conv2D + GlobalPooling classifier, plus the supporting text tooling
(sentence/document iterators, stemming preprocessors, POS filtering,
SentiWordNet polarity, constituency-tree utilities).

Reference counterparts: iterator/CnnSentenceDataSetIterator.java,
text/sentenceiterator + documentiterator packages, nlp-uima's
StemmingPreprocessor/PosUimaTokenizer/SWN3/treeparser.

Run: PYTHONPATH=/root/repo:/root/.axon_site python examples/20_text_classification_pipeline.py
"""

import random

import jax

jax.config.update("jax_platforms", "cpu")  # small demo; skip the TPU tunnel

import numpy as np

from deeplearning4j_tpu.nlp import (
    PorterStemmer,
    PosTokenizerFactory,
    StemmingPreprocessor,
    SWN3,
    Tree,
    TreeVectorizer,
    Word2Vec,
)
from deeplearning4j_tpu.nlp.cnn_sentence import (
    CnnSentenceDataSetIterator,
    CollectionLabeledSentenceProvider,
)
from deeplearning4j_tpu.nn.conf import InputType, NeuralNetConfiguration
from deeplearning4j_tpu.nn.layers import ConvolutionLayer, OutputLayer
from deeplearning4j_tpu.nn.layers.pooling import GlobalPoolingLayer
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork

# --- 1. train word vectors on a toy corpus --------------------------------
animals = ["cat dog purr bark fur", "dog cat tail paw fur",
           "cat purr fur paw bark"]
tech = ["cpu gpu cache chip core", "gpu cpu silicon chip core",
        "cpu cache chip core silicon"]
corpus = [s.split() for s in (animals + tech) * 30]
w2v = Word2Vec(layer_size=16, window_size=3, min_word_frequency=1,
               seed=7, epochs=10)
w2v.fit(corpus)
print(f"word2vec: {w2v.vocab.num_words()} words, "
      f"nearest to 'cat': {w2v.words_nearest('cat', 3)}")

# --- 2. CNN sentence batches ----------------------------------------------
sents, labels = [], []
for s in animals * 8:
    sents.append(s), labels.append("animal")
for s in tech * 8:
    sents.append(s), labels.append("tech")
provider = CollectionLabeledSentenceProvider(sents, labels,
                                             rng=random.Random(3))
it = CnnSentenceDataSetIterator(provider, w2v, minibatch_size=8,
                                max_sentence_length=5,
                                feature_format="NHWC")
print(f"labels: {it.get_labels()}, word-vector size {it.input_columns()}")

# --- 3. Kim-style conv classifier -----------------------------------------
conf = (NeuralNetConfiguration.builder().seed(5).updater("adam").list()
        .layer(ConvolutionLayer(n_out=8, kernel_size=(2, 16),
                                convolution_mode="same", activation="relu"))
        .layer(GlobalPoolingLayer(pooling_type="max"))
        .layer(OutputLayer(n_out=2))
        .set_input_type(InputType.convolutional(5, 16, 1))
        .build())
net = MultiLayerNetwork(conf).init()
for _ in range(30):
    for ds in it:
        net.fit(ds.features, ds.labels)

correct = total = 0
it.reset()
for ds in it:
    out = np.asarray(net.output(ds.features))
    correct += int((out.argmax(1) == ds.labels.argmax(1)).sum())
    total += len(out)
print(f"sentence-CNN train accuracy: {correct / total:.2f}")
pred = np.asarray(net.output(it.load_single_sentence("purr paw fur")))
print(f"'purr paw fur' -> {it.get_labels()[int(pred.argmax())]}")

# --- 4. the supporting text tooling ---------------------------------------
stem = PorterStemmer()
print("stems:", [stem.stem(w) for w in ["motoring", "relational", "ponies"]])
pre = StemmingPreprocessor()
print("stemming preprocessor:", pre.pre_process("Conflated,"))

pos = PosTokenizerFactory(allowed_pos_tags={"NN", "NNS"}, strip_nones=True)
print("nouns only:", pos.create("the cat is running quickly").get_tokens())

swn = SWN3()
for text in ("a good movie", "not a good movie", "terrible awful plot"):
    print(f"sentiment {text!r}: {swn.classify(text)}")

tree = Tree.from_penn(
    "(S (NP (DT the) (NN cat)) (VP (VBZ sits) (PP (IN on) (NP (DT the) (NN mat)))))")
tv = TreeVectorizer()
[normalized] = tv.get_trees_with_labels([tree.to_penn()], "pos", ["neg", "pos"])
print("tree yield:", normalized.yield_words(),
      "gold label on root:", normalized.gold_label)
