"""Causal language model: training, KV-cached decoding, device-side sampling.

The decoder-side twin of example 10: a GPT-style `TransformerLM` (causal
self-attention with a fixed-capacity KV cache riding the same recurrent-carry
protocol as the LSTMs) trained on a next-token task, then sampled three ways:

1. `generate`      — host loop over `rnn_time_step` (one jitted step/token);
2. `generate_on_device` — the WHOLE decode compiled to one executable
   (prefill + `lax.scan` + on-device sampling). Measured on one TPU v5e
   through a remote link: 1.37 ms/token vs the host loop's 116 ms/token —
   85x, because the per-token host round trip disappears (BASELINE.md);
3. truncated BPTT — the same model trained in chunks with carried caches
   (Transformer-XL-style), via the graph's `t_bptt_length`.

Also shows SameDiff-style control flow is unrelated to decoding: the KV
cache makes stepwise decode O(T·cache) instead of O(T^2) re-forwards.

Run: python examples/11_transformer_lm_generation.py   (CPU-friendly)
"""

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.zoo.models import (
    TransformerLM,
    generate,
    generate_on_device,
    lm_labels,
)

VOCAB = 11


def cycle(rng, n, t, step=3):
    start = rng.integers(0, VOCAB, size=(n, 1))
    return ((start + step * np.arange(t)[None, :]) % VOCAB).astype(np.float32)


def main():
    rng = np.random.default_rng(0)

    # -- train a tiny decoder on the +3 successor rule ----------------------
    m = TransformerLM(vocab_size=VOCAB, max_length=32, n_layers=2,
                      d_model=32, n_heads=4, d_ff=64, seed=3)
    net = ComputationGraph(m.conf()).init()
    x = cycle(rng, 64, 32)
    y = lm_labels(x, VOCAB)
    lmask = np.ones(x.shape[:2], np.float32)
    lmask[:, -1] = 0.0                       # final step has no next token
    ds = DataSet(x, y, labels_mask=lmask)
    s0 = net.score(ds)
    for _ in range(150):
        net.fit(ds)
    print(f"LM loss: {s0:.3f} -> {net.score_:.3f} after 150 steps")

    # -- decode: host loop vs single-dispatch device loop -------------------
    prompt = cycle(np.random.default_rng(1), 2, 6)
    host = generate(net, prompt, 8)                      # rnn_time_step loop
    dev = generate_on_device(net, prompt, 8)             # one lax.scan
    want = (prompt[:, -1:] + 3 * np.arange(1, 9)[None, :]) % VOCAB
    print(f"host loop continues the cycle:   {(host == want).mean():.2f}")
    print(f"device loop identical to host:   {(host == dev).all()}")
    sampled = generate_on_device(net, prompt, 8, temperature=0.8, seed=4)
    print(f"temperature sampling (device):   {sampled[0].tolist()}")

    # -- truncated BPTT over the DAG: chunked training, carried KV caches ---
    conf = TransformerLM(vocab_size=VOCAB, max_length=32, n_layers=1,
                         d_model=16, n_heads=2, d_ff=32, seed=5).conf()
    conf.backprop_type = "truncated_bptt"
    conf.tbptt_fwd_length = 8                # 4 chunks per 32-step sequence
    tb = ComputationGraph(conf).init()
    for _ in range(20):
        tb.fit(ds)
    print(f"TBPTT (4 chunks/batch): loss {tb.score_:.3f}, "
          f"iterations {tb.iteration} (one per chunk)")


if __name__ == "__main__":
    main()
